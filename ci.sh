#!/usr/bin/env bash
# CI entry point — the analogue of the reference's .travis.yml script
# section: run the full test suite, then smoke-run two examples under
# the launcher at np=2 (the reference runs tensorflow_mnist.py and a
# shrunk keras_mnist_advanced.py under `mpirun -np 2`).
set -euxo pipefail
cd "$(dirname "$0")"

JAX_PLATFORMS=cpu python -m pytest tests/ -q

# hvdlint gate (docs/analysis.md): the JAX-aware static analyzer must
# be clean against the committed baseline — which this repo ships
# EMPTY, so ANY finding (a host sync sneaking into the @hot_path tick
# ring, trace-unsafe control flow, an unregistered env knob, ...)
# fails CI here. The gate's failure mode is proven by
# tests/test_analysis.py::TestCIGate with a deliberately-violating
# temp file, so CI itself stays green-on-clean.
JAX_PLATFORMS=cpu python -m horovod_tpu.analysis \
    --baseline .hvdlint-baseline.json
# Env-knob discipline beyond the package: bench/bench_daemon read
# HVD_* knobs too — HVD005 (only; bench's exception style is its own)
# keeps them inside the runtime/config.py registry so the generated
# troubleshooting table stays complete.
JAX_PLATFORMS=cpu python -m horovod_tpu.analysis --rules HVD005 \
    bench.py bench_daemon.py

# Runtime lock witness (docs/analysis.md "The runtime witness"): the
# dynamic half of HVD007. Re-run the lock-heaviest suites (serving
# engine/router, resilience, elastic membership) with every registered
# lock ARMED (HVD_LOCK_CHECK=1) — each acquisition feeds the witness's
# order graph. The dump must show ZERO observed order inversions (an
# inversion here is a deadlock the suite actually walked), and
# tests/test_lockcheck.py separately pins that observed edges are a
# subset of the static lock_order_graph.
rm -f /tmp/hvd_lock_witness.json
HVD_LOCK_CHECK=1 HVD_LOCK_CHECK_OUT=/tmp/hvd_lock_witness.json \
    JAX_PLATFORMS=cpu python -m pytest -q \
    tests/test_serving.py tests/test_router.py \
    tests/test_resilience.py tests/test_membership.py
python - <<'EOF'
import json
snap = json.load(open("/tmp/hvd_lock_witness.json"))
assert snap["inversions"] == [], (
    "lock witness observed order inversions:\n"
    + json.dumps(snap["inversions"], indent=2))
print(f"lock witness: {sum(len(v) for v in snap['edges'].values())} "
      f"edge(s), 0 inversions")
EOF

# Compat matrix (the reference sweeps {py27/34/36} x {TF 1.1/1.4/
# nightly} x {OpenMPI,MPICH} in .travis.yml; this image pins ONE real
# generation — TF 2.21 / Keras 3 — so the other Keras generations'
# optimizer surfaces are driven explicitly by stub optimizers of each
# generation's API). One leg per interception path
# (horovod/keras/__init__.py): Keras-3 apply_gradients via the real
# optimizer (test_fit_decreases_loss), Keras-2 get_gradients and
# TF2-legacy _compute_gradients via the generation stubs. The tests
# run in the full suite above; this collect-only step is the named
# guard that each generation leg still exists (a rename/removal fails
# CI here even if the suite still passes).
JAX_PLATFORMS=cpu python -m pytest -q --collect-only \
    "tests/test_tf_compat.py::TestKeras::test_fit_decreases_loss" \
    "tests/test_tf_compat.py::TestCompatRegressions::test_keras2_get_gradients_path_averages" \
    "tests/test_tf_compat.py::TestCompatRegressions::test_tf2_legacy_compute_gradients_path_averages" \
    > /dev/null

# Serving-engine smoke: 4 concurrent requests through the continuous-
# batching engine on CPU; asserts completion AND token-exactness vs
# sequential generate (the engine's oracle contract), PLUS the PR-3
# hot-path guarantees: --warmup pins that program warmup happened (no
# XLA compile inside the timed serving window, compiles == 0) and
# --interleave-check pins that TPOT under a concurrent long-prompt
# admission stays within 2x the idle-pool TPOT (interleaved chunked
# prefill; bound loose enough for CPU CI). --obs-check is the
# observability smoke (docs/observability.md): the metrics exporter
# comes up on an EPHEMERAL port, /metrics is fetched over real HTTP
# and must expose the serving + resilience + training metric families
# from the shared registry in ONE scrape, and /healthz must show the
# live engine's dispatch generation. --prefix-check is the paged-KV
# smoke (PR 7, docs/serving.md "Paged KV cache"): two requests sharing
# a 48-token system prompt through a PAGED engine — the second must
# report prefill-tokens-skipped > 0 (prefix served from resident
# blocks) and TTFT strictly below the cold request's, both token-exact
# vs sequential generate. --spec-check is the decode-fast-path smoke
# (PR 13, docs/serving.md "Decode fast path"): a speculative
# (self-draft) engine's greedy streams must be BITWISE the plain
# engine's with >= 1 multi-token round observed.
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 4 \
    --warmup --interleave-check --obs-check --prefix-check --spec-check

# Overload-control smoke (PR 17, docs/serving.md "Overload control"):
# two tenants (HVD_TENANT_WEIGHTS-style weighted lanes) against a TINY
# paged pool — a low-priority "free" flood saturates it, then a
# priority-5 "paid" request must be admitted by token-exact PREEMPTION
# (bounded TTFT, not parked behind the flood). Two phases pin both
# resume modes: >= 1 swap preemption (KV blocks shelved in host RAM
# and re-grafted on resume) and >= 1 recompute preemption
# (swap_bytes=0: forced-prefix re-prefill). Every stream must be
# bitwise the unpressured run's and no flood request may starve (the
# WFQ aging guarantee). Knobs: HVD_PREEMPT, HVD_SWAP_BYTES,
# HVD_TENANT_WEIGHTS, HVD_BROWNOUT (runtime/config.py registry).
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 2 \
    --preempt-check

# Fleet-observability smoke (docs/observability.md "Fleet view" /
# "Flight recorder"): on a 2-engine host, one /fleet scrape must show
# the fleet-merged hvd_fleet_* histograms (both engines' requests
# pooled) and hvd_rank_skew_* gauges; then the env-armed chaos fault
# (serving_dispatch_crash, deferred by the example until a request is
# in flight) must be healed by the watchdog AND leave a
# flight-recorder bundle in HVD_FLIGHT_DIR whose pretty-printer
# output names the ring's newest event and the crashed request's
# trace_id — the end-to-end post-mortem proof. The module CLI is then
# exercised on the bundle directly. hvdlint above already proves the
# new obs modules (aggregate/straggler/flightrec/slo) sit on the
# EMPTY baseline.
rm -rf /tmp/hvd_fleet_smoke
HVD_CHAOS=serving_dispatch_crash:1 HVD_FLIGHT_DIR=/tmp/hvd_fleet_smoke \
    JAX_PLATFORMS=cpu python examples/transformer_serving.py \
    --requests 2 --fleet-check
JAX_PLATFORMS=cpu python -m horovod_tpu.obs.flightrec \
    "$(ls /tmp/hvd_fleet_smoke/flight_*.json | tail -1)" \
    | grep -q "trace_id="

# Request-tracing smoke (PR 20, docs/observability.md "Request
# tracing" / "Record/replay"): under a scoped SpanRecorder one
# request's causal span tree must decompose into the FULL serving
# anatomy — the printed waterfall shows the queue_wait/admission/
# prefill/decode phase tags and the phase anatomy sums to within 5%
# of the client-observed latency (no unattributed wall-clock).
# Then 8 client arrivals are recorded to an obs.reqlog JSONL,
# prompt-synthesized back from their digests, and re-served on a
# fresh engine: request count and every per-request token count must
# round-trip exactly — the record->replay guarantee bench.py's
# --record-reqlog/--replay flags build on. Knobs: HVD_TRACE_LOG,
# HVD_TRACE_SAMPLE, HVD_REQLOG (runtime/config.py registry).
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 2 \
    --trace-check

# Serving-fleet failover smoke (docs/serving.md "Fleet failover"):
# three in-process ServingEngine replicas behind a ServingRouter; the
# router.replica_kill chaos site hard-kills the busiest replica while
# streams are mid-decode. All requests must complete, migrated
# streams must be BITWISE a no-chaos run's (token-exact migration:
# already-generated tokens resubmitted as a forced prefix, sample
# stream resumed at the right ordinal), and the fleet must be back at
# full strength via a cold replacement.
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 4 \
    --failover-check

# Sharded-serving smoke (docs/serving.md "Sharded serving"): the
# example bootstraps a 4-device virtual CPU mesh
# (--xla_force_host_platform_device_count) and asserts (1) fixed AND
# paged engines sharded over a model=4 mesh produce BITWISE the
# unsharded engine's token streams, greedy and seeded — the mesh
# changes where the hot path runs, never what it produces — and (2) a
# MIXED sharded/unsharded fleet under ServingRouter survives a
# router.replica_kill mid-decode with every stream token-exact vs the
# no-chaos run (forced-prefix migration is layout-agnostic).
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 3 \
    --sharded-check

# Disaggregated-serving smoke (docs/serving.md "Disaggregated
# serving"): a prefill pool and a decode pool behind a DisaggRouter —
# every stream prefills on one engine, hands its KV blocks (digest-
# verified manifest) to the other, and resumes mid-flight BITWISE the
# shared-program engine's stream, with the full prompt blocks grafted
# into the decode pool's prefix cache (only the sub-block tail
# re-prefills). A chaos-corrupted transfer (disagg.block_corrupt)
# must be rejected by byte-digest verification and the stream
# recovered via token-level recompute — still bitwise.
JAX_PLATFORMS=cpu python examples/transformer_serving.py --requests 4 \
    --disagg-check

# Resume smoke (docs/resilience.md "Exact resume"): a short training
# run over a sharded shuffled dataset is killed mid-epoch AND
# mid-checkpoint-save via HVD_CHAOS, restarted with full TrainSnapshot
# resume (model + data cursor + guard), and equivalence-checked
# against an uninterrupted control — the batch streams must be
# bitwise identical, final params must match, and the resume gap must
# be 0 (the module exits nonzero otherwise, and also if no kill
# actually fired — an inert smoke proves nothing).
rm -rf /tmp/hvd_resume_smoke
HVD_CHAOS=train_crash:2,ckpt_kill:1 JAX_PLATFORMS=cpu \
    python -m horovod_tpu.resilience.equivalence \
    --workdir /tmp/hvd_resume_smoke --epochs 2 --save-every 2 \
    2>&1 | tee /tmp/hvd_resume_smoke.log
grep -q "equivalence OK" /tmp/hvd_resume_smoke.log

# Elastic-membership smoke (docs/resilience.md "Elastic membership"):
# a 4-member in-process simulated world trains under an env-armed
# rank_death — one member stops heartbeating mid-epoch, the survivors
# must detect the lapsed lease, commit generation 1, shrink to 3,
# roll back to the last committed TrainSnapshot, rebalance shards,
# and finish every epoch with the union of all members' effective
# per-record streams bitwise-equal (as a multiset) to an
# uninterrupted control run's — no record trained twice, none
# silently dropped (the module exits nonzero otherwise, and also if
# the death or the resize never actually happened).
rm -rf /tmp/hvd_elastic_smoke
HVD_CHAOS=rank_death:1 JAX_PLATFORMS=cpu \
    python -m horovod_tpu.resilience.equivalence --resize \
    --workdir /tmp/hvd_elastic_smoke \
    2>&1 | tee /tmp/hvd_elastic_smoke.log
grep -q "resize equivalence OK" /tmp/hvd_elastic_smoke.log

# Multi-controller elastic smoke (docs/resilience.md "The
# multi-process drill"): the REAL thing — hvdrun launches 3 worker
# processes over the native rendezvous KV server (--elastic: a signal
# death is a membership event, not a job failure), each worker
# installs BootstrapKV as its membership transport and trains in
# KV-coordinated lockstep (no cross-process jax collectives), worker
# 2 SIGKILLs itself mid-epoch, the survivors' shared FailureDetector
# sees the lease lapse, the resize protocol commits generation 1,
# bootstrap.apply_resize re-keys the runtime, and training resumes
# from the committed TrainSnapshot with the shard remainder
# rebalanced — the driver verifies the surviving world's final states
# agree bitwise and the effective per-record union equals every
# dataset record exactly once per epoch, then prints the OK line.
rm -rf /tmp/hvd_elastic_mc
JAX_PLATFORMS=cpu python -m horovod_tpu.resilience.drill \
    --workdir /tmp/hvd_elastic_mc --world 3 --kill-rank 2 \
    2>&1 | tee /tmp/hvd_elastic_mc.log
grep -q "resize equivalence OK (multi-process)" /tmp/hvd_elastic_mc.log

# Chaos smoke (docs/resilience.md): one injected checkpoint-write
# failure mid-run — the shared RetryPolicy must retry with backoff and
# the run must still complete and leave a restorable checkpoint.
rm -rf /tmp/hvd_chaos_smoke
HVD_CHAOS=ckpt_write_fail:1 JAX_PLATFORMS=cpu \
    python examples/jax_checkpoint_resume.py --steps 10 --save-every 5 \
    --ckpt-dir /tmp/hvd_chaos_smoke 2>&1 | tee /tmp/hvd_chaos_smoke.log
grep -q "retry 1/" /tmp/hvd_chaos_smoke.log       # the retry happened
grep -q "final loss" /tmp/hvd_chaos_smoke.log     # ...and run finished
test -d /tmp/hvd_chaos_smoke/step_00000010        # ...with the save

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/jax_mnist.py --steps 20

# Compressed-allreduce leg: DistributedOptimizer(compression=powersgd)
# composed with the CNN step factory (single reduce), multi-process.
python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/jax_mnist.py --steps 20 --compression powersgd

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/jax_mnist_advanced.py --epochs 1

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/torch_mnist.py --steps 20

echo "CI OK"
