#!/usr/bin/env bash
# CI entry point — the analogue of the reference's .travis.yml script
# section: run the full test suite, then smoke-run two examples under
# the launcher at np=2 (the reference runs tensorflow_mnist.py and a
# shrunk keras_mnist_advanced.py under `mpirun -np 2`).
set -euxo pipefail
cd "$(dirname "$0")"

JAX_PLATFORMS=cpu python -m pytest tests/ -q

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/jax_mnist.py --steps 20

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/jax_mnist_advanced.py --epochs 1

python -m horovod_tpu.runner -np 2 --platform cpu -- \
    python examples/torch_mnist.py --steps 20

echo "CI OK"
