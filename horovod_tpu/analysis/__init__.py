"""hvdlint — JAX-aware static analysis for horovod_tpu.

An AST-based analyzer (stdlib only) with a rule framework tuned to
this repo's bug classes: host syncs in the pipelined serving hot path
(HVD001), trace-unsafe Python control flow in compiled functions
(HVD002), recompilation hazards (HVD003), mixed lock discipline
(HVD004), environment knobs bypassing the config registry (HVD005),
and swallowed broad excepts (HVD006). See docs/analysis.md for the
catalog, the ``# hvd: disable=RULE(reason)`` suppression syntax, and
the baseline workflow; ``ci.sh`` gates on
``python -m horovod_tpu.analysis --baseline .hvdlint-baseline.json``.
"""

from horovod_tpu.analysis.core import (  # noqa: F401
    Finding, Project, RuleMeta, collect_files, run_rules,
)
from horovod_tpu.analysis.cli import analyze, main  # noqa: F401
from horovod_tpu.analysis.rules import ALL_RULES, BY_ID  # noqa: F401
