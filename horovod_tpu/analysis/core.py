"""hvdlint core: findings, suppressions, source files, the driver.

The analysis itself is stdlib-only (``ast`` + ``tokenize``) — no new
dependencies, nothing heavier than parsing in CI. (The ``python -m
horovod_tpu.analysis`` entry still imports the parent package, so the
CLI needs a working install; the analysis modules themselves do not
touch jax.) A *rule* is a module under
`horovod_tpu.analysis.rules` exporting

    RULE = RuleMeta(id="HVD00x", ...)
    def check(project: Project) -> Iterable[Finding]

Rules see the whole `Project` (every parsed file plus the cross-file
`SymbolTable`), so per-file visitors and whole-program checks (call
graphs, registries) share one framework.

Suppressions
------------
A finding is suppressed by a ``# hvd: disable=RULE`` comment either on
the finding's line or on a standalone comment line directly above it::

    x = dev_val.item()       # hvd: disable=HVD001(the designed sync)

    # hvd: disable=HVD006(shutdown must proceed past any fault)
    except Exception:

Multiple rules separate with commas; the parenthesized reason is
optional syntax but required culture — the shipped tree carries a
reason on every suppression (docs/analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning")

_SUPPRESS_RE = re.compile(r"#\s*hvd:\s*disable=([^#]*)")
_RULE_ID_RE = re.compile(r"[A-Z][A-Z0-9_]*")


def _parse_rule_tokens(spec: str) -> Dict[str, str]:
    """Parse ``RULE(reason), RULE2(reason2), ...`` from a disable
    comment. The grammar is strict on both sides so prose can never
    mute a rule by accident: reasons are matched to their CLOSING
    paren with a depth counter (``HVD004(abandon() is benign)`` stays
    one suppression with the full reason — a first-')' cut would
    register the ALL-CAPS words after it as extra muted rules), and
    rules chain ONLY through a comma (trailing prose like
    ``HVD005(ok) but HVD001-style ...`` ends the list instead of
    muting HVD001)."""
    rules: Dict[str, str] = {}
    i, n = 0, len(spec)
    while True:
        while i < n and spec[i].isspace():
            i += 1
        if rules:            # subsequent rules require a ',' joiner
            if i >= n or spec[i] != ",":
                break
            i += 1
            while i < n and spec[i].isspace():
                i += 1
        m = _RULE_ID_RE.match(spec, i)
        if not m:
            break
        rid = m.group(0)
        i = m.end()
        while i < n and spec[i].isspace():
            i += 1
        reason = ""
        if i < n and spec[i] == "(":
            depth, j = 1, i + 1
            while j < n and depth:
                if spec[j] == "(":
                    depth += 1
                elif spec[j] == ")":
                    depth -= 1
                j += 1
            # Unbalanced open paren: the reason runs to end of comment.
            reason = spec[i + 1:j - 1] if depth == 0 else spec[i + 1:]
            i = j
        rules[rid] = reason
    return rules


@dataclasses.dataclass(frozen=True)
class RuleMeta:
    """Static description of one rule (the catalog row)."""

    id: str                  # "HVD001"
    name: str                # "host-sync-in-hot-path"
    severity: str            # "error" | "warning"
    doc: str                 # one-paragraph catalog entry


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str                # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift with unrelated edits,
        so a baselined finding matches on (rule, path, message)."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed file: AST, raw lines, and the suppression map."""

    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=abspath)
        # line (1-based) -> {rule_id: reason}
        self.suppressions: Dict[int, Dict[str, str]] = {}
        self._scan_suppressions()

    def _scan_suppressions(self):
        # Real COMMENT tokens only (tokenize, not a raw line regex):
        # a "# hvd: disable=..." inside a string or docstring is TEXT
        # — honoring it could silently mute a genuine finding on the
        # next code line.
        comments: Dict[int, str] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []     # ast parsed, so this is belt-and-braces
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
        pending: Dict[str, str] = {}
        for i, raw in enumerate(self.lines, start=1):
            comment = comments.get(i, "")
            m = _SUPPRESS_RE.search(comment)
            stripped = raw.strip()
            if m:
                rules = _parse_rule_tokens(m.group(1))
                if stripped.startswith("#"):
                    # Standalone comment: applies to the next code line
                    # (accumulating across consecutive comment lines).
                    pending.update(rules)
                else:
                    here = dict(self.suppressions.get(i, {}))
                    here.update(pending)
                    here.update(rules)
                    self.suppressions[i] = here
                    pending = {}
            elif stripped.startswith("#"):
                continue    # a contiguous comment block keeps `pending`
            elif not stripped:
                # A blank line severs the "directly above" link: a
                # suppression whose statement was deleted must die with
                # it, not silently migrate onto the next code below.
                pending = {}
            else:
                if pending:
                    self.suppressions[i] = dict(pending)
                    pending = {}

    def suppressed(self, rule: str, line: int) -> bool:
        return rule in self.suppressions.get(line, ())


class Project:
    """Everything a rule can see: the file set and the symbol table."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self.by_path = {f.path: f for f in files}
        from horovod_tpu.analysis.symbols import SymbolTable
        self.symbols = SymbolTable(files)

    def file_of(self, relpath: str) -> Optional[SourceFile]:
        return self.by_path.get(relpath)


def collect_files(paths: Iterable[str], root: str) -> List[SourceFile]:
    """Parse every ``.py`` under ``paths`` (files or directories);
    relpaths are taken against ``root``. Syntax errors propagate — an
    unparseable tree must fail the lint run, not silently shrink it."""
    seen = set()
    out: List[SourceFile] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            todo = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                todo += [os.path.join(dirpath, fn)
                         for fn in filenames if fn.endswith(".py")]
        elif ap.endswith(".py"):
            todo = [ap]
        else:
            raise FileNotFoundError(f"not a python file or dir: {p}")
        for f in sorted(todo):
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root)
            if rel.startswith(".."):
                rel = f
            with open(f, "r", encoding="utf-8") as fh:
                out.append(SourceFile(f, rel, fh.read()))
    return out


def run_rules(project: Project, rules) -> Tuple[List[Finding],
                                                List[Finding]]:
    """Run ``rules`` over ``project``; returns (active, suppressed)
    findings, both sorted by (path, line, rule)."""
    active: List[Finding] = []
    muted: List[Finding] = []
    for rule_mod in rules:
        for finding in rule_mod.check(project):
            src = project.file_of(finding.path)
            if src is not None and src.suppressed(finding.rule,
                                                  finding.line):
                muted.append(finding)
            else:
                active.append(finding)
    keyfn = lambda f: (f.path, f.line, f.col, f.rule)  # noqa: E731
    return sorted(active, key=keyfn), sorted(muted, key=keyfn)


# -- small AST helpers shared by rules --------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_scope(node: ast.AST):
    """ast.walk that does NOT descend into nested function/class
    definitions — the per-scope traversal lock/except rules need."""
    todo = list(ast.iter_child_nodes(node))
    while todo:
        n = todo.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            todo.extend(ast.iter_child_nodes(n))
