"""hvdlint command line.

::

    python -m horovod_tpu.analysis [paths...]
        [--baseline .hvdlint-baseline.json] [--write-baseline]
        [--json] [--rules HVD001,HVD004] [--list-rules]
        [--write-env-table [docs/troubleshooting.md]]
        [--write-chaos-table [docs/resilience.md]]

Exit codes: 0 clean (all findings baselined), 1 findings, 2 usage or
analysis error. Default target: the installed ``horovod_tpu`` package
tree. The baseline defaults to ``.hvdlint-baseline.json`` in the
current directory for BOTH reading and ``--write-baseline`` (a missing
file is an empty baseline), so the CI gate is just ``python -m
horovod_tpu.analysis`` from the repo root.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from horovod_tpu.analysis import baseline as baseline_mod
from horovod_tpu.analysis.core import Project, collect_files, run_rules
from horovod_tpu.analysis.rules import ALL_RULES, BY_ID

_ENV_TABLE_BEGIN = "<!-- hvdlint:env-table:begin -->"
_ENV_TABLE_END = "<!-- hvdlint:env-table:end -->"
_CHAOS_TABLE_BEGIN = "<!-- hvdlint:chaos-table:begin -->"
_CHAOS_TABLE_END = "<!-- hvdlint:chaos-table:end -->"


def _package_root() -> str:
    import horovod_tpu
    return os.path.dirname(os.path.abspath(horovod_tpu.__file__))


def _repo_root() -> str:
    return os.path.dirname(_package_root())


def analyze(paths, rules=None, root=None):
    """API twin of the CLI: (active, suppressed) findings for
    ``paths`` (defaults: whole package, all rules)."""
    root = root or _repo_root()
    paths = list(paths) if paths else [_package_root()]
    files = collect_files(paths, root)
    project = Project(files)
    return run_rules(project, rules or ALL_RULES), len(files)


def _write_marked_table(doc_path: str, begin: str, end: str,
                        table_md: str) -> bool:
    """Replace the span between ``begin``/``end`` markers in
    ``doc_path`` with ``table_md``. Returns True when the file
    changed."""
    with open(doc_path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
    except ValueError:
        raise SystemExit(
            f"{doc_path}: missing {begin} / {end} markers")
    new = f"{head}{begin}\n{table_md}{end}{tail}"
    if new != text:
        with open(doc_path, "w", encoding="utf-8") as fh:
            fh.write(new)
        return True
    return False


def write_env_table(doc_path: str) -> bool:
    """Regenerate the environment-knob table between the hvdlint
    markers in ``doc_path`` from the live config registry. Returns
    True when the file changed."""
    from horovod_tpu.runtime.config import env_table_md
    return _write_marked_table(doc_path, _ENV_TABLE_BEGIN,
                               _ENV_TABLE_END, env_table_md())


def write_chaos_table(doc_path: str) -> bool:
    """Regenerate the chaos-site table between the hvdlint markers in
    ``doc_path`` from a source scan (`chaos.site_table_md`) — the
    docs cannot name a site the code no longer instruments, and a new
    site cannot ship undocumented. Returns True when the file
    changed."""
    from horovod_tpu.resilience.chaos import site_table_md
    return _write_marked_table(doc_path, _CHAOS_TABLE_BEGIN,
                               _CHAOS_TABLE_END, site_table_md())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: JAX-aware static analysis for "
                    "horovod_tpu (docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "horovod_tpu package)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON; findings recorded there do "
                         "not fail the run (default: read "
                         ".hvdlint-baseline.json in the current "
                         "directory; missing file = empty baseline)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into --baseline "
                         "(default .hvdlint-baseline.json) and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--rules", metavar="IDS",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-env-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "troubleshooting.md"),
                    help="regenerate the env-knob table in DOC from "
                         "the config registry, then exit")
    ap.add_argument("--write-chaos-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "resilience.md"),
                    help="regenerate the chaos-site table in DOC from "
                         "a source scan, then exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for mod in ALL_RULES:
            r = mod.RULE
            print(f"{r.id}  {r.name:28s} [{r.severity}]  {r.doc}")
        return 0

    if args.write_env_table:
        changed = write_env_table(args.write_env_table)
        print(f"hvdlint: env table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_env_table}")
        return 0

    if args.write_chaos_table:
        changed = write_chaos_table(args.write_chaos_table)
        print(f"hvdlint: chaos-site table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_chaos_table}")
        return 0

    rules = ALL_RULES
    if args.rules:
        try:
            rules = [BY_ID[rid.strip()]
                     for rid in args.rules.split(",") if rid.strip()]
        except KeyError as e:
            ap.error(f"unknown rule id {e.args[0]!r} "
                     f"(see --list-rules)")

    try:
        (active, muted), nfiles = analyze(args.paths, rules)
    except (SyntaxError, OSError, UnicodeDecodeError) as e:
        # Any unreadable/unparseable input is exit 2 (usage/analysis
        # error), never a traceback the gate can't tell from findings.
        print(f"hvdlint: {e}", file=sys.stderr)
        return 2

    # The default is symmetric: plain runs READ the same cwd ledger
    # --write-baseline writes, so the documented adopt workflow
    # (snapshot, then a plain run exits 0) holds without flags.
    baseline_path = args.baseline or ".hvdlint-baseline.json"
    if args.write_baseline:
        baseline_mod.save(baseline_path, active)
        print(f"hvdlint: wrote {len(active)} finding(s) to "
              f"{baseline_path}")
        return 0

    baselined = baseline_mod.load(baseline_path)
    new, old = baseline_mod.split(active, baselined)

    if args.json:
        print(json.dumps({
            "files": nfiles,
            "findings": [f.to_json() for f in new],
            "baselined": len(old),
            "suppressed": [f.to_json() for f in muted],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        errs = sum(1 for f in new if f.severity == "error")
        if new:
            print(f"hvdlint: {len(new)} finding(s) ({errs} error(s), "
                  f"{len(new) - errs} warning(s)) in {nfiles} files; "
                  f"{len(old)} baselined, {len(muted)} suppressed")
        else:
            print(f"hvdlint: clean ({nfiles} files, {len(old)} "
                  f"baselined, {len(muted)} suppressed)")
    return 1 if new else 0
