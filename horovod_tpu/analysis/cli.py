"""hvdlint command line.

::

    python -m horovod_tpu.analysis [paths...]
        [--baseline .hvdlint-baseline.json] [--write-baseline]
        [--json] [--rules HVD001,HVD004] [--list-rules]
        [--changed-only]
        [--write-env-table [docs/troubleshooting.md]]
        [--write-chaos-table [docs/resilience.md]]
        [--write-event-table [docs/observability.md]]
        [--write-span-table [docs/observability.md]]

Exit codes: 0 clean (all findings baselined), 1 findings, 2 usage or
analysis error. Default target: the installed ``horovod_tpu`` package
tree. The baseline defaults to ``.hvdlint-baseline.json`` in the
current directory for BOTH reading and ``--write-baseline`` (a missing
file is an empty baseline), so the CI gate is just ``python -m
horovod_tpu.analysis`` from the repo root.

``--changed-only`` is the edit-loop accelerator: the WHOLE package is
still parsed (the symbol table, the lock graph and the drift catalogs
need every module), but findings are reported only for files changed
vs the git merge-base (plus the working tree and untracked files) and
for files that import a changed module — the blast radius of the
edit. CI keeps the full walk.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import subprocess
import sys

from horovod_tpu.analysis import baseline as baseline_mod
from horovod_tpu.analysis.core import Project, collect_files, run_rules
from horovod_tpu.analysis.rules import ALL_RULES, BY_ID

_ENV_TABLE_BEGIN = "<!-- hvdlint:env-table:begin -->"
_ENV_TABLE_END = "<!-- hvdlint:env-table:end -->"
_CHAOS_TABLE_BEGIN = "<!-- hvdlint:chaos-table:begin -->"
_CHAOS_TABLE_END = "<!-- hvdlint:chaos-table:end -->"
_EVENT_TABLE_BEGIN = "<!-- hvdlint:event-table:begin -->"
_EVENT_TABLE_END = "<!-- hvdlint:event-table:end -->"
_SPAN_TABLE_BEGIN = "<!-- hvdlint:span-table:begin -->"
_SPAN_TABLE_END = "<!-- hvdlint:span-table:end -->"


def _package_root() -> str:
    import horovod_tpu
    return os.path.dirname(os.path.abspath(horovod_tpu.__file__))


def _repo_root() -> str:
    return os.path.dirname(_package_root())


def analyze(paths, rules=None, root=None, changed_only=False):
    """API twin of the CLI: (active, suppressed) findings for
    ``paths`` (defaults: whole package, all rules). With
    ``changed_only``, the full file set is still parsed and analyzed
    but findings are restricted to `changed_scope`."""
    root = root or _repo_root()
    paths = list(paths) if paths else [_package_root()]
    files = collect_files(paths, root)
    project = Project(files)
    active, muted = run_rules(project, rules or ALL_RULES)
    if changed_only:
        scope = changed_scope(project, root)
        active = [f for f in active if f.path in scope]
        muted = [f for f in muted if f.path in scope]
    return (active, muted), len(files)


def _git_changed_files(root):
    """Repo-relative paths changed vs the merge-base with the default
    branch, plus working-tree and untracked changes. Empty on any git
    failure (not a repo, no main ref) — caller treats that as 'no
    scope', exit 2."""
    def _run(*args):
        try:
            out = subprocess.run(
                ["git", "-C", root, *args], capture_output=True,
                text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return out.stdout if out.returncode == 0 else None

    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        got = _run("merge-base", "HEAD", ref)
        if got:
            base = got.strip()
            break
    changed = set()
    diffs = [_run("diff", "--name-only", base)] if base else []
    diffs.append(_run("diff", "--name-only", "HEAD"))
    diffs.append(_run("ls-files", "--others", "--exclude-standard"))
    saw_git = False
    for out in diffs:
        if out is None:
            continue
        saw_git = True
        changed |= {ln.strip() for ln in out.splitlines() if ln.strip()}
    return changed if saw_git else None


def changed_scope(project, root):
    """The ``--changed-only`` reporting scope: analyzed files changed
    per git, plus every analyzed file that imports a changed module
    (its contracts — signatures, locks, metric names — may have moved
    under it). Imports are scanned over the whole tree, not just the
    top level, because this codebase imports obs/* function-locally."""
    changed = _git_changed_files(root)
    if changed is None:
        raise SystemExit("hvdlint: --changed-only requires a git "
                         "checkout (git diff failed)")
    symbols = project.symbols
    seed = {p for p in symbols.modules if p in changed}
    scope = set(seed)
    for path, mi in symbols.modules.items():
        if path in seed:
            continue
        for node in ast.walk(mi.src.tree):
            dotted = []
            if isinstance(node, ast.Import):
                dotted = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                dotted = [node.module] + [
                    f"{node.module}.{a.name}" for a in node.names]
            if any((t := symbols.module_by_dotted(d)) is not None
                   and t.path in seed for d in dotted):
                scope.add(path)
                break
    return scope


def _write_marked_table(doc_path: str, begin: str, end: str,
                        table_md: str) -> bool:
    """Replace the span between ``begin``/``end`` markers in
    ``doc_path`` with ``table_md``. Returns True when the file
    changed."""
    with open(doc_path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
    except ValueError:
        raise SystemExit(
            f"{doc_path}: missing {begin} / {end} markers")
    new = f"{head}{begin}\n{table_md}{end}{tail}"
    if new != text:
        with open(doc_path, "w", encoding="utf-8") as fh:
            fh.write(new)
        return True
    return False


def write_env_table(doc_path: str) -> bool:
    """Regenerate the environment-knob table between the hvdlint
    markers in ``doc_path`` from the live config registry. Returns
    True when the file changed."""
    from horovod_tpu.runtime.config import env_table_md
    return _write_marked_table(doc_path, _ENV_TABLE_BEGIN,
                               _ENV_TABLE_END, env_table_md())


def write_chaos_table(doc_path: str) -> bool:
    """Regenerate the chaos-site table between the hvdlint markers in
    ``doc_path`` from a source scan (`chaos.site_table_md`) — the
    docs cannot name a site the code no longer instruments, and a new
    site cannot ship undocumented. Returns True when the file
    changed."""
    from horovod_tpu.resilience.chaos import site_table_md
    return _write_marked_table(doc_path, _CHAOS_TABLE_BEGIN,
                               _CHAOS_TABLE_END, site_table_md())


def write_event_table(doc_path: str) -> bool:
    """Regenerate the structured-event table between the hvdlint
    markers in ``doc_path`` from `obs.events.EVENT_CATALOG` — the
    same catalog HVD011 pins against the emit sites, so the doc can
    neither name an event nothing emits nor miss one that ships.
    Returns True when the file changed."""
    from horovod_tpu.obs.events import event_table_md
    return _write_marked_table(doc_path, _EVENT_TABLE_BEGIN,
                               _EVENT_TABLE_END, event_table_md())


def write_span_table(doc_path: str) -> bool:
    """Regenerate the request-tracing span table between the hvdlint
    markers in ``doc_path`` from `obs.spans.SPAN_CATALOG` — the same
    catalog HVD012 pins against the record sites, so the doc can
    neither name a span nothing records nor miss one that ships.
    Returns True when the file changed."""
    from horovod_tpu.obs.spans import span_table_md
    return _write_marked_table(doc_path, _SPAN_TABLE_BEGIN,
                               _SPAN_TABLE_END, span_table_md())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: JAX-aware static analysis for "
                    "horovod_tpu (docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "horovod_tpu package)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON; findings recorded there do "
                         "not fail the run (default: read "
                         ".hvdlint-baseline.json in the current "
                         "directory; missing file = empty baseline)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into --baseline "
                         "(default .hvdlint-baseline.json) and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--rules", metavar="IDS",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for files changed vs "
                         "the git merge-base (plus files importing "
                         "them); the whole package is still parsed")
    ap.add_argument("--write-env-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "troubleshooting.md"),
                    help="regenerate the env-knob table in DOC from "
                         "the config registry, then exit")
    ap.add_argument("--write-chaos-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "resilience.md"),
                    help="regenerate the chaos-site table in DOC from "
                         "a source scan, then exit")
    ap.add_argument("--write-event-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "observability.md"),
                    help="regenerate the structured-event table in "
                         "DOC from obs.events.EVENT_CATALOG, then "
                         "exit")
    ap.add_argument("--write-span-table", nargs="?", metavar="DOC",
                    const=os.path.join(_repo_root(), "docs",
                                       "observability.md"),
                    help="regenerate the request-tracing span table "
                         "in DOC from obs.spans.SPAN_CATALOG, then "
                         "exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for mod in ALL_RULES:
            r = mod.RULE
            print(f"{r.id}  {r.name:28s} [{r.severity}]  {r.doc}")
        return 0

    if args.write_env_table:
        changed = write_env_table(args.write_env_table)
        print(f"hvdlint: env table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_env_table}")
        return 0

    if args.write_chaos_table:
        changed = write_chaos_table(args.write_chaos_table)
        print(f"hvdlint: chaos-site table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_chaos_table}")
        return 0

    if args.write_event_table:
        changed = write_event_table(args.write_event_table)
        print(f"hvdlint: event table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_event_table}")
        return 0

    if args.write_span_table:
        changed = write_span_table(args.write_span_table)
        print(f"hvdlint: span table "
              f"{'updated' if changed else 'already current'} in "
              f"{args.write_span_table}")
        return 0

    rules = ALL_RULES
    if args.rules:
        try:
            rules = [BY_ID[rid.strip()]
                     for rid in args.rules.split(",") if rid.strip()]
        except KeyError as e:
            ap.error(f"unknown rule id {e.args[0]!r} "
                     f"(see --list-rules)")

    try:
        (active, muted), nfiles = analyze(
            args.paths, rules, changed_only=args.changed_only)
    except (SyntaxError, OSError, UnicodeDecodeError) as e:
        # Any unreadable/unparseable input is exit 2 (usage/analysis
        # error), never a traceback the gate can't tell from findings.
        print(f"hvdlint: {e}", file=sys.stderr)
        return 2

    # The default is symmetric: plain runs READ the same cwd ledger
    # --write-baseline writes, so the documented adopt workflow
    # (snapshot, then a plain run exits 0) holds without flags.
    baseline_path = args.baseline or ".hvdlint-baseline.json"
    if args.write_baseline:
        baseline_mod.save(baseline_path, active)
        print(f"hvdlint: wrote {len(active)} finding(s) to "
              f"{baseline_path}")
        return 0

    baselined = baseline_mod.load(baseline_path)
    new, old = baseline_mod.split(active, baselined)

    if args.json:
        by_rule = {}
        for f in new:
            by_rule.setdefault(f.rule, {"findings": 0,
                                        "suppressed": 0})
            by_rule[f.rule]["findings"] += 1
        for f in muted:
            by_rule.setdefault(f.rule, {"findings": 0,
                                        "suppressed": 0})
            by_rule[f.rule]["suppressed"] += 1
        print(json.dumps({
            "files": nfiles,
            "findings": [f.to_json() for f in new],
            "baselined": len(old),
            "suppressed": [f.to_json() for f in muted],
            "by_rule": {r: by_rule[r] for r in sorted(by_rule)},
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        errs = sum(1 for f in new if f.severity == "error")
        if new:
            print(f"hvdlint: {len(new)} finding(s) ({errs} error(s), "
                  f"{len(new) - errs} warning(s)) in {nfiles} files; "
                  f"{len(old)} baselined, {len(muted)} suppressed")
        else:
            print(f"hvdlint: clean ({nfiles} files, {len(old)} "
                  f"baselined, {len(muted)} suppressed)")
    return 1 if new else 0
