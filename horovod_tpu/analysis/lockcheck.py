"""Runtime lock witness — the dynamic half of HVD007.

``HVD_LOCK_CHECK=1`` arms it: `register(name, lock)` then returns a
recording proxy instead of the raw lock, and every acquisition made
anywhere in the process appends to a per-thread held stack and a
global edge set ``(held, acquired)`` with the first witness (thread
name, file:line of the acquire). Two consistency properties fall out:

* an **inversion** — edge ``(b, a)`` observed when ``(a, b)`` already
  was — is a deadlock the test run actually walked (two threads just
  didn't interleave badly enough this time); the CI leg runs the
  serving + resilience suites armed and fails on any inversion;
* the observed graph must be a **subset** of HVD007's static
  acquisition graph (`lock_order.lock_order_graph`) — a runtime edge
  the static analysis missed is a resolver gap, pinned by a test.

Unarmed (the default), `register` hands back the raw lock object —
zero wrappers, zero overhead, nothing imported beyond this module.
Lock names follow the static convention: ``ClassName.attr`` for
instance locks, ``modstem.GLOBAL`` for module-level locks, so the two
graphs diff key-for-key.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["LockWitness", "register", "enabled", "default_witness"]


def enabled() -> bool:
    from horovod_tpu.runtime.config import env_int
    return env_int("HVD_LOCK_CHECK", 0) != 0


class LockWitness:
    """Acquisition-order recorder. Thread-safe; its own mutex is a
    raw Lock (never registered — the witness must not witness
    itself)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (held, acquired) -> first witness "thread @ file:line"
        self.edges: Dict[Tuple[str, str], str] = {}
        self.inversions: List[Dict] = []
        self._inverted_pairs = set()

    def _stack(self) -> List[str]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @staticmethod
    def _site() -> str:
        # Innermost frame outside this module = the acquire site.
        for frame in reversed(traceback.extract_stack()):
            if os.path.basename(frame.filename) != "lockcheck.py":
                return f"{frame.filename}:{frame.lineno}"
        return "?"

    def acquired(self, name: str):
        stack = self._stack()
        first = name not in stack    # reentrant re-acquire adds no edge
        stack.append(name)
        if not first:
            return
        held = [n for n in dict.fromkeys(stack[:-1]) if n != name]
        if not held:
            return
        witness = f"{threading.current_thread().name} @ {self._site()}"
        with self._mu:
            for h in held:
                key = (h, name)
                if key not in self.edges:
                    self.edges[key] = witness
                inv = (name, h)
                if inv in self.edges:
                    pair = tuple(sorted((h, name)))
                    if pair not in self._inverted_pairs:
                        self._inverted_pairs.add(pair)
                        self.inversions.append({
                            "pair": list(pair),
                            "first": {"order": list(inv),
                                      "witness": self.edges[inv]},
                            "second": {"order": list(key),
                                       "witness": witness},
                        })

    def released(self, name: str):
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def wrap(self, name: str, lock):
        return _LockProxy(self, name, lock)

    def graph(self) -> Dict[str, List[str]]:
        with self._mu:
            out: Dict[str, List[str]] = {}
            for (a, b) in self.edges:
                out.setdefault(a, []).append(b)
        for succs in out.values():
            succs.sort()
        return out

    def snapshot(self) -> Dict:
        graph = self.graph()
        with self._mu:
            return {"edges": graph,
                    "witnesses": {f"{a} -> {b}": w
                                  for (a, b), w in self.edges.items()},
                    "inversions": list(self.inversions)}

    def dump(self, path: str):
        with open(path, "w") as fh:
            json.dump(self.snapshot(), fh, indent=2, sort_keys=True)
            fh.write("\n")


class _LockProxy:
    """Context-manager + acquire/release facade over a real lock; the
    subset of the Lock/RLock API this codebase uses (`with`, and
    `locked()` in assertions)."""

    def __init__(self, witness: LockWitness, name: str, lock):
        self._witness = witness
        self._name = name
        self._lock = lock

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._witness.acquired(self._name)
        return got

    def release(self):
        self._lock.release()
        self._witness.released(self._name)

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockcheck {self._name} {self._lock!r}>"


_DEFAULT: Optional[LockWitness] = None
_DEFAULT_MU = threading.Lock()


def default_witness() -> LockWitness:
    """The process-global witness (created on first armed register)."""
    global _DEFAULT
    with _DEFAULT_MU:
        if _DEFAULT is None:
            _DEFAULT = LockWitness()
            _install_dump_hook()
        return _DEFAULT


def register(name: str, lock):
    """Wrap ``lock`` under the static graph's node ``name`` when
    ``HVD_LOCK_CHECK=1``; hand the raw lock back otherwise. Wrap at
    construction: ``self._lock = lockcheck.register("Cls._lock",
    threading.Lock())`` — hvdlint's lock discovery sees through the
    call."""
    if not enabled():
        return lock
    return default_witness().wrap(name, lock)


def _install_dump_hook():
    """At exit, write the order graph to ``HVD_LOCK_CHECK_OUT`` (the
    CI leg's zero-inversion evidence) and warn on inversions."""
    import atexit

    def _dump():
        w = _DEFAULT
        if w is None:
            return
        from horovod_tpu.runtime.config import env_str
        out = env_str("HVD_LOCK_CHECK_OUT")
        if out:
            try:
                w.dump(out)
            except OSError as e:
                sys.stderr.write(
                    f"lockcheck: cannot write {out!r}: {e}\n")
        for inv in w.inversions:
            sys.stderr.write(
                f"lockcheck: ORDER INVERSION {inv['pair']}: "
                f"{inv['first']['order']} at "
                f"{inv['first']['witness']} vs "
                f"{inv['second']['order']} at "
                f"{inv['second']['witness']}\n")

    atexit.register(_dump)
