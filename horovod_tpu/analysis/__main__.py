import sys

from horovod_tpu.analysis.cli import main

sys.exit(main())
