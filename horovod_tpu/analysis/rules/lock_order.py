"""HVD007: lock-order cycles (potential deadlock).

Builds the static lock-acquisition graph: an edge ``A -> B`` means
some execution path acquires lock ``B`` while holding lock ``A`` —
either a ``with`` nested lexically inside another ``with``, or a call
made while holding ``A`` whose (transitively resolved) callee acquires
``B``. Cross-object edges resolve through the attribute-type map
(``self.queue = RequestQueue()`` makes ``len(self.queue)`` under the
engine lock an ``Engine._lock -> RequestQueue._lock`` edge, dunder
protocols included). A cycle in this graph is a potential deadlock:
two threads walking the cycle from different nodes block each other
forever. Each cycle is reported once, with the witness path — the
acquisition sites that close it.

The graph itself is exported (`lock_order_graph`) because the runtime
lock witness (`horovod_tpu.analysis.lockcheck`, ``HVD_LOCK_CHECK=1``)
records the *observed* acquisition graph during the test suite and a
test asserts observed ⊆ static — the dynamic analysis validates the
static one's completeness, the static one bounds the dynamic one's
coverage.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from horovod_tpu.analysis.core import Finding, RuleMeta
from horovod_tpu.analysis.rules._threads import (
    local_class_types, thread_world, walk_with_locks,
)

RULE = RuleMeta(
    id="HVD007",
    name="lock-order-cycle",
    severity="error",
    doc="Cycle in the static lock-acquisition graph (lock B taken "
        "while holding A on one path, A while holding B on another) "
        "— a potential deadlock between the threads that walk the "
        "two paths.")

# witness: (holder, acquired) -> (path, line, via)
Edges = Dict[Tuple[str, str], Tuple[str, int, str]]


def _direct_acquires(world, fi, aliases, local_types) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for item in node.items:
                ln = world.lock_node(item.context_expr, fi, aliases,
                                     local_types)
                if ln:
                    out.add(ln)
    return out


def _fn_ctx(world, fi):
    mi = world.project.symbols.modules[fi.module]
    local_types = local_class_types(fi.node, mi,
                                    world.project.symbols)
    aliases = world.lock_aliases(fi, local_types)
    return local_types, aliases


def _transitive_acquires(world, fi, memo, stack) -> Set[str]:
    """Locks ``fi`` may acquire, directly or through resolved calls.
    Recursion through a call cycle contributes what is known so far
    (an under-approximation only inside the cycle — every function is
    also analyzed as a root, so its own edges are never lost)."""
    if fi.qname in memo:
        return memo[fi.qname]
    if fi.qname in stack:
        return set()
    stack.add(fi.qname)
    local_types, aliases = _fn_ctx(world, fi)
    out = _direct_acquires(world, fi, aliases, local_types)
    for node in ast.walk(fi.node):
        callees = []
        if isinstance(node, ast.Call):
            callees += world.resolve_precise(fi, node, local_types)
        callees += world.protocol_callees(fi, node, local_types)
        for c in callees:
            out |= _transitive_acquires(world, c, memo, stack)
    stack.discard(fi.qname)
    memo[fi.qname] = out
    return out


def _collect_edges(project) -> Edges:
    world = thread_world(project)
    memo: Dict[str, Set[str]] = {}
    edges: Edges = {}

    def add_edge(holder, acquired, path, line, via):
        if holder == acquired:
            return    # reentrancy is HVD-not-this-rule's problem
        edges.setdefault((holder, acquired), (path, line, via))

    for fi in project.symbols.all_functions():
        local_types, aliases = _fn_ctx(world, fi)

        def on_acquire(ln, expr, held, fi=fi):
            for h in held:
                add_edge(h, ln, fi.src.path, expr.lineno,
                         "nested with")

        def on_node(node, held, fi=fi, local_types=local_types):
            if not held:
                return
            callees = []
            if isinstance(node, ast.Call):
                callees += world.resolve_precise(fi, node,
                                                 local_types)
            callees += world.protocol_callees(fi, node, local_types)
            for c in callees:
                for acq in _transitive_acquires(world, c, memo,
                                                set()):
                    for h in held:
                        add_edge(h, acq, fi.src.path, node.lineno,
                                 f"call into {c.qname}")

        walk_with_locks(world, fi, aliases, local_types,
                        on_acquire=on_acquire, on_node=on_node)
    return edges


def lock_order_graph(project) -> Dict[str, List[str]]:
    """{lock-node: sorted successor lock-nodes} — the static
    acquisition graph the runtime witness is diffed against."""
    out: Dict[str, List[str]] = {}
    for (a, b) in _collect_edges(project):
        out.setdefault(a, [])
        if b not in out[a]:
            out[a].append(b)
    for succs in out.values():
        succs.sort()
    return out


def _cycles(edges: Edges) -> List[List[str]]:
    """Minimal cycles, one per strongly-connected component with >1
    node (self-edges are filtered at collection). Deterministic: DFS
    from the lexicographically smallest node over sorted successors."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    for succs in graph.values():
        succs.sort()

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph[v]:
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    out = []
    for comp in sorted(sccs):
        start = comp[0]
        members = set(comp)
        # Shortest path start -> ... -> start inside the SCC (BFS).
        prev = {start: None}
        todo = [(start, 0)]
        cycle = None
        while todo and cycle is None:
            v, _ = todo.pop(0)
            for w in graph[v]:
                if w == start:
                    path = [start]
                    node = v
                    while node is not None:
                        path.append(node)
                        node = prev[node]
                    cycle = list(reversed(path[1:])) + [start] \
                        if len(path) > 1 else [start, start]
                    break
                if w in members and w not in prev:
                    prev[w] = v
                    todo.append((w, 0))
        if cycle:
            out.append(cycle)
    return out


def check(project):
    edges = _collect_edges(project)
    for cycle in _cycles(edges):
        # cycle = [a, b, ..., a]; witness each hop.
        hops = []
        for a, b in zip(cycle, cycle[1:]):
            path, line, via = edges[(a, b)]
            hops.append((a, b, path, line, via))
        first = hops[0]
        detail = "; ".join(
            f"{b} taken holding {a} at {p}:{ln} ({via})"
            for a, b, p, ln, via in hops)
        yield Finding(
            RULE.id, RULE.severity, first[2], first[3], 0,
            f"lock-order cycle "
            f"{' -> '.join(cycle)} — potential deadlock: {detail}")
