"""HVD009: blocking operations inside a held-lock scope.

A lock on the serving or coordination path is a shared-state fence,
not a place to wait: ``time.sleep`` under a lock turns every other
acquirer into a sleeper too (a latency cliff); ``Thread.join`` /
``Event.wait`` / blocking ``queue.get`` under a lock is a deadlock
rung (the joined thread may need that very lock to finish); socket
and subprocess waits under a lock stall the plane on a peer; and
``block_until_ready`` / ``jax.device_get`` under a lock serializes
device completion into every contender's critical section.

Flagged lexically: a blocking call while at least one ``with <lock>``
scope is open in the same function. ``Condition.wait`` on the very
condition being held is the designed sleep-with-release pattern and
is exempt; ``Event.wait`` / ``lock.acquire(timeout=...)`` on *other*
objects is not.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from horovod_tpu.analysis.core import Finding, RuleMeta, dotted_name
from horovod_tpu.analysis.rules._threads import (
    local_class_types, thread_world, walk_with_locks,
)

RULE = RuleMeta(
    id="HVD009",
    name="blocking-under-lock",
    severity="warning",
    doc="A blocking operation (sleep, Thread.join, Event/Condition "
        "wait on another object, blocking queue get/put, socket or "
        "subprocess wait, block_until_ready/device_get) inside an "
        "open `with <lock>` scope — a latency cliff or deadlock "
        "rung for every other acquirer.")

# Dotted-call names that block outright.
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "jax.device_get": "jax.device_get",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "os.waitpid": "os.waitpid",
    "os.wait": "os.wait",
}

# Method leaves that block when invoked on a thread/event/queue/
# socket/process-shaped receiver.
_BLOCKING_METHODS = {"join", "wait", "get", "put", "recv", "send",
                     "sendall", "accept", "connect", "communicate",
                     "block_until_ready", "result"}

# Receiver kinds (from constructor tracking) that make those method
# names blocking.
_BLOCKING_CTORS = {
    "threading.Thread": "Thread", "Thread": "Thread",
    "threading.Event": "Event", "Event": "Event",
    "queue.Queue": "Queue", "Queue": "Queue",
    "queue.SimpleQueue": "Queue",
    "socket.socket": "socket",
    "subprocess.Popen": "Popen", "Popen": "Popen",
}

_KIND_METHODS = {
    "Thread": {"join"},
    "Event": {"wait"},
    "Queue": {"get", "put", "join"},
    "socket": {"recv", "send", "sendall", "accept", "connect"},
    "Popen": {"wait", "communicate"},
}


def _blocking_attr_kinds(ci) -> Dict[str, str]:
    """{attr: kind} for self attributes assigned a thread/event/queue/
    socket/process anywhere in the class."""
    out: Dict[str, str] = {}
    for method in ci.methods.values():
        for node in ast.walk(method.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            kind = _BLOCKING_CTORS.get(
                dotted_name(node.value.func) or "")
            if kind is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out.setdefault(tgt.attr, kind)
    return out


def _local_kinds(fn_node) -> Dict[str, str]:
    from horovod_tpu.analysis.core import walk_scope
    out: Dict[str, str] = {}
    for node in walk_scope(fn_node):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            kind = _BLOCKING_CTORS.get(
                dotted_name(node.value.func) or "")
            if kind is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, kind)
    return out


def _is_nonblocking_call(call: ast.Call) -> bool:
    """``q.get(block=False)`` / ``q.get_nowait()`` style calls do not
    block; ``h.result(timeout=0)`` still does (it raises later but
    waits first is version-dependent — keep it flagged unless
    block=False)."""
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def check(project):
    world = thread_world(project)
    for mi in project.symbols.modules.values():
        classes = list(mi.classes.values())
        for ci in classes + [None]:
            methods = (ci.methods.values() if ci
                       else mi.functions.values())
            attr_kinds = _blocking_attr_kinds(ci) if ci else {}
            for fi in methods:
                yield from _scan_function(world, fi, attr_kinds)


def _scan_function(world, fi, attr_kinds):
    mi = world.project.symbols.modules[fi.module]
    local_types = local_class_types(fi.node, mi,
                                    world.project.symbols)
    aliases = world.lock_aliases(fi, local_types)
    local_kinds = _local_kinds(fi.node)
    findings = []

    def receiver_kind(expr) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return attr_kinds.get(expr.attr)
        if isinstance(expr, ast.Name):
            return local_kinds.get(expr.id)
        return None

    def classify(call: ast.Call, held) -> Optional[str]:
        name = dotted_name(call.func) or ""
        if name in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[name]
        if not isinstance(call.func, ast.Attribute):
            return None
        leaf = call.func.attr
        if leaf not in _BLOCKING_METHODS:
            return None
        if leaf == "block_until_ready":
            return "block_until_ready"
        recv = call.func.value
        # Condition.wait on the HELD condition releases it while
        # sleeping — the designed pattern, not a finding.
        recv_lock = world.lock_node(recv, fi, aliases, local_types)
        if recv_lock is not None and recv_lock in held:
            return None
        kind = receiver_kind(recv)
        if kind is None:
            return None
        if leaf in _KIND_METHODS.get(kind, ()):
            if leaf in ("get", "put") and _is_nonblocking_call(call):
                return None
            return f"{kind}.{leaf}"
        return None

    def on_node(node, held):
        if held and isinstance(node, ast.Call):
            what = classify(node, held)
            if what is not None:
                findings.append(Finding(
                    RULE.id, RULE.severity, fi.src.path, node.lineno,
                    node.col_offset,
                    f"blocking {what} while holding "
                    f"{', '.join(held)} in "
                    f"{fi.qname.split(':')[-1]} — a latency cliff "
                    f"(or deadlock rung) for every other acquirer"))

    walk_with_locks(world, fi, aliases, local_types, on_node=on_node)
    return findings
