"""HVD001: host synchronization inside the serving/decode hot path.

The PR-3 pipelining win (host_syncs_per_token 0.279 -> 0.034) rests on
ONE exposed device->host sync per request: the dispatch thread queues
tick N+1 before reading tick N. A single stray ``.item()`` /
``np.asarray`` / ``block_until_ready`` on a device value anywhere in
that path silently re-serializes the ring — the device idles while the
host blocks, every tick. This rule walks the call graph from every
``@hot_path``-annotated entry (`horovod_tpu.annotations.hot_path`) and
flags the sync patterns inside the reachable set:

* ``x.item()`` / ``x.tolist()`` / ``x.block_until_ready()``
* ``np.asarray(x)`` / ``np.array(x)`` / ``jax.device_get(x)`` —
  through module aliases AND bare-name from-imports (any alias)
* ``int(x)`` / ``float(x)`` / ``bool(x)`` where ``x`` was produced by
  a known ``jax.jit``-compiled callee (local value taint)

Designed sync points (e.g. the pipelined ``tick_sync`` read itself)
carry a reasoned ``# hvd: disable=HVD001(...)``.
"""

from __future__ import annotations

import ast

from horovod_tpu.analysis.core import Finding, RuleMeta, dotted_name

RULE = RuleMeta(
    id="HVD001",
    name="host-sync-in-hot-path",
    severity="error",
    doc="Device->host synchronization reachable from a @hot_path "
        "entry point re-serializes the pipelined decode ring.")

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_MODULES = {"numpy"}
_NUMPY_FUNCS = {"asarray", "array", "copy"}
_CASTS = {"int", "float", "bool"}


def _numpy_alias_map(mi):
    """Local aliases of the numpy module in this file ('np', ...)."""
    return {alias for alias, dotted in mi.module_aliases.items()
            if dotted in _NUMPY_MODULES}


def _from_import_syncs(mi) -> dict:
    """{local name: message} for host-sync functions bound as bare
    names — ``from numpy import asarray``, ``from jax import
    device_get`` (any alias)."""
    out = {}
    for local, (mod, orig) in mi.from_imports.items():
        if mod in _NUMPY_MODULES and orig in _NUMPY_FUNCS:
            out[local] = (f"{mod}.{orig}() copies device memory to "
                          f"host")
        elif mod == "jax" and orig == "device_get":
            out[local] = "jax.device_get() blocks on a device value"
    return out


def _jit_tainted_locals(fi, table, mi, ci) -> set:
    """Names assigned (incl. tuple-unpacked) from calls to known
    jit-compiled callees within this function."""
    tainted = set()
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callees = table.resolve_call(mi, ci, node.value)
        fi0 = callees[0] if callees else None
        if not table.is_jit_callee(fi0, mi, node.value):
            continue
        for tgt in node.targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                if isinstance(el, ast.Name):
                    tainted.add(el.id)
    return tainted


def _root_name(node: ast.AST):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def check(project):
    table = project.symbols
    reach = table.hot_reachable()
    for qname in sorted(reach):
        fi, entry = reach[qname]
        mi = table.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None
        np_aliases = _numpy_alias_map(mi)
        import_syncs = _from_import_syncs(mi)
        tainted = _jit_tainted_locals(fi, table, mi, ci)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in _SYNC_METHODS):
                msg = f".{fn.attr}() blocks on a device value"
            elif isinstance(fn, ast.Attribute):
                base = fn.value
                if (isinstance(base, ast.Name)
                        and base.id in np_aliases
                        and fn.attr in _NUMPY_FUNCS):
                    msg = (f"{base.id}.{fn.attr}() copies device "
                           f"memory to host")
                elif (fn.attr == "device_get"
                      and isinstance(base, ast.Name)
                      and (base.id == "jax"
                           or mi.module_aliases.get(base.id)
                           == "jax")):
                    msg = "jax.device_get() blocks on a device value"
            elif isinstance(fn, ast.Name) and fn.id in import_syncs:
                msg = import_syncs[fn.id]
            elif (isinstance(fn, ast.Name) and fn.id in _CASTS
                  and node.args):
                arg = node.args[0]
                root = _root_name(arg)
                if (root in tainted
                        or (isinstance(arg, ast.Call)
                            and table.is_jit_callee(
                                (table.resolve_call(mi, ci, arg)
                                 or [None])[0], mi, arg))):
                    msg = (f"{fn.id}() forces a device->host read "
                           f"of a jit-produced value")
            if msg is not None:
                yield Finding(
                    RULE.id, RULE.severity, fi.src.path, node.lineno,
                    node.col_offset,
                    f"host sync in hot path: {msg} inside "
                    f"{fi.qname.split(':')[1]} (reachable from "
                    f"@hot_path {entry})")
