"""HVD005: HVD_*/HOROVOD_* environment reads outside the registry.

`horovod_tpu/runtime/config.py` is the single source of truth for
every environment knob: each variable is declared with
``register_knob(...)`` (name, type, default, consumer, doc — the
generated docs/troubleshooting.md table) and consumed through the
``env_str``/``env_int``/``env_float``/``env_raw`` accessors, which
refuse undeclared names at runtime. A raw ``os.environ`` read of an
``HVD_*``/``HOROVOD_*`` name anywhere else creates an undocumented,
untabulated knob that silently drifts — this rule flags:

* ``os.environ.get("HVD_X")`` / ``os.environ["HVD_X"]`` /
  ``os.getenv("HVD_X")`` / ``"HVD_X" in os.environ`` outside the
  registry module — including reads through a local ``env =
  os.environ`` alias and through ``from os import environ, getenv``
  bindings (any alias). Alias tracking is LEXICALLY SCOPED: an alias
  is visible in its own scope and nested defs, a parameter shadows
  it (a mapping argument that merely shares the name ``env`` is not
  os.environ). Writes/deletes are NOT flagged (arming a knob
  in-process sets the environment, it doesn't bypass the accessors);
* ``env_str("HVD_X")``-style accessor calls whose literal name is not
  declared in the registry (the static twin of the runtime KeyError).
"""

from __future__ import annotations

import ast
import re

from horovod_tpu.analysis.core import (
    Finding, RuleMeta, const_str, dotted_name, walk_scope,
)

RULE = RuleMeta(
    id="HVD005",
    name="unregistered-env-knob",
    severity="error",
    doc="os.environ read of an HVD_*/HOROVOD_* variable outside the "
        "runtime/config.py knob registry (or an accessor call with an "
        "undeclared name).")

_KNOB_RE = re.compile(r"^(HVD_|HOROVOD_)")
_REGISTRY_MODULE = "runtime/config.py"
_ACCESSORS = {"env_str", "env_int", "env_float", "env_raw"}


def _registered_names(project) -> set:
    """Knob names harvested from register_knob("NAME", ...) calls in
    the registry module's AST. When the registry module is not part of
    the analyzed file set (subtree runs), fall back to the installed
    live registry so accessor calls against real knobs don't produce
    phantom findings."""
    out = set()
    saw_registry = False
    for mi in project.symbols.modules.values():
        if not mi.path.endswith(_REGISTRY_MODULE):
            continue
        saw_registry = True
        for node in ast.walk(mi.src.tree):
            if (isinstance(node, ast.Call)
                    and (dotted_name(node.func) or "").split(".")[-1]
                    == "register_knob" and node.args):
                name = const_str(node.args[0])
                if name:
                    out.add(name)
    if not saw_registry:
        try:
            from horovod_tpu.runtime.config import KNOBS
            out |= set(KNOBS)
        except ImportError:  # analyzing a foreign tree — static only
            pass
    return out


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _scope_aliases(scope, environs, getenvs) -> tuple:
    """Aliases visible inside ``scope``: the inherited sets minus
    names shadowed by the scope's own parameters (a mapping parameter
    that merely SHARES a name with an alias elsewhere is not
    os.environ), plus plain-assignment aliases bound in this scope's
    body (``env = os.environ``, ``g = os.getenv``, chained to a
    fixpoint)."""
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
        a = scope.args
        params = ({p.arg for p in a.posonlyargs}
                  | {p.arg for p in a.args}
                  | {p.arg for p in a.kwonlyargs}
                  | ({a.vararg.arg} if a.vararg else set())
                  | ({a.kwarg.arg} if a.kwarg else set()))
        environs = environs - params
        getenvs = getenvs - params
    else:
        environs, getenvs = set(environs), set(getenvs)
    changed = True
    while changed:
        changed = False
        for node in walk_scope(scope):
            if not isinstance(node, ast.Assign):
                continue
            src = dotted_name(node.value) or ""
            tgts = {t.id for t in node.targets
                    if isinstance(t, ast.Name)}
            if src == "os.environ" or src in environs:
                if tgts - environs:
                    environs |= tgts
                    changed = True
            elif src == "os.getenv" or src in getenvs:
                if tgts - getenvs:
                    getenvs |= tgts
                    changed = True
    return environs, getenvs


def check(project):
    registered = _registered_names(project)
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_REGISTRY_MODULE):
            continue
        environs, getenvs = set(), set()
        for local, (mod, orig) in mi.from_imports.items():
            if mod == "os" and orig == "environ":
                environs.add(local)
            elif mod == "os" and orig == "getenv":
                getenvs.add(local)
        yield from _scan_scope(mi, mi.src.tree, environs, getenvs,
                               registered)


def _scan_scope(mi, scope, environs, getenvs, registered):
    environs, getenvs = _scope_aliases(scope, environs, getenvs)
    for node in walk_scope(scope):
            name = None
            kind = None
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func) or ""
                leaf = fn.split(".")[-1]
                base = fn.rsplit(".", 1)[0] if "." in fn else ""
                if ((fn in ("os.environ.get", "os.getenv")
                     or (leaf == "get" and base in environs)
                     or fn in getenvs)
                        and node.args):
                    name = const_str(node.args[0])
                    kind = "raw os.environ read"
                elif leaf in _ACCESSORS and node.args:
                    nm = const_str(node.args[0])
                    if nm and nm not in registered:
                        yield Finding(
                            RULE.id, RULE.severity, mi.path,
                            node.lineno, node.col_offset,
                            f"env knob {nm!r} read via {leaf}() but "
                            f"never declared with register_knob() in "
                            f"horovod_tpu/runtime/config.py")
                    continue
            elif isinstance(node, ast.Compare):
                # `"HVD_X" in os.environ` — the presence-flag read
                # pattern; use env_raw(...) is not None instead.
                if (len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))):
                    base = dotted_name(node.comparators[0]) or ""
                    if base == "os.environ" or base in environs:
                        name = const_str(node.left)
                        kind = "os.environ membership test"
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)):
                # Load context only: writes/deletes (arming a knob
                # in-process) SET the environment, they don't bypass
                # the registry's read accessors.
                base = dotted_name(node.value) or ""
                if (base == "os.environ"
                        or base in environs):
                    name = const_str(node.slice)
                    kind = "raw os.environ read"
            if name and _KNOB_RE.match(name):
                yield Finding(
                    RULE.id, RULE.severity, mi.path, node.lineno,
                    node.col_offset,
                    f"{kind} of {name!r} outside the "
                    f"runtime/config.py knob registry — declare it "
                    f"with register_knob() and read it via "
                    f"env_str/env_int/env_float")
    for node in walk_scope(scope):
        if isinstance(node, _SCOPE_NODES):
            yield from _scan_scope(mi, node, environs, getenvs,
                                   registered)
