"""hvdlint rule registry.

Each rule is a sibling module exporting ``RULE`` (a
`core.RuleMeta`) and ``check(project)``. Order here is catalog order.
"""

from __future__ import annotations

from horovod_tpu.analysis.rules import (
    host_sync,
    trace_safety,
    recompile,
    locks,
    env_registry,
    broad_except,
    lock_order,
    cross_thread,
    blocking_lock,
    metric_catalog,
    event_docs,
    span_catalog,
)

ALL_RULES = [host_sync, trace_safety, recompile, locks, env_registry,
             broad_except, lock_order, cross_thread, blocking_lock,
             metric_catalog, event_docs, span_catalog]

BY_ID = {mod.RULE.id: mod for mod in ALL_RULES}
