"""HVD006: swallowed broad excepts outside marked recovery code.

``except Exception`` that neither re-raises nor raises a typed wrapper
turns programming errors into silent state corruption — in a codebase
whose recovery layer (watchdog restarts, chaos drills, checkpoint
fallbacks) *depends* on faults surfacing, a swallowed broad except is
a disabled smoke detector. The rule flags ``except``/``except
Exception``/``except BaseException`` handlers (and tuples containing
them) whose body contains no ``raise``; intentionally-broad recovery
handlers stay, marked ``# hvd: disable=HVD006(reason)`` — the reason
is the documentation reviewers actually read.
"""

from __future__ import annotations

import ast

from horovod_tpu.analysis.core import Finding, RuleMeta, walk_scope

RULE = RuleMeta(
    id="HVD006",
    name="swallowed-broad-except",
    severity="warning",
    doc="`except Exception` (or broader) with no raise in the handler "
        "body swallows programming errors; narrow it or mark recovery "
        "code with a reasoned suppression.")

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True          # bare except:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    return False


def check(project):
    for mi in project.symbols.modules.values():
        for node in ast.walk(mi.src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            raises = any(isinstance(n, ast.Raise)
                         for stmt in node.body
                         for n in [stmt, *walk_scope(stmt)])
            if raises:
                continue
            shown = (f"except {ast.unparse(node.type)}"
                     if node.type is not None else "bare except:")
            yield Finding(
                RULE.id, RULE.severity, mi.path, node.lineno,
                node.col_offset,
                f"broad `{shown}` swallows the fault (no "
                f"raise in handler) — narrow to the exceptions this "
                f"path can actually recover from, or mark recovery "
                f"code with # hvd: disable=HVD006(reason)")
