"""HVD012: span names drifting from the SPAN_CATALOG contract.

`horovod_tpu.obs.spans.SPAN_CATALOG` declares every causal span name
the subsystems may record, with the one-line description an operator
reads in docs/observability.md's span table. Phase attribution hangs
off the same names (`SPAN_PHASE`), so drift is worse than a missing
doc row: an undeclared span is invisible to the critical-path
anatomy. Two drift directions break the contract:

* a ``spans.begin_span("name", ...)`` / ``spans.record_span(...)``
  call (through any alias of the spans module, including
  function-local imports) with a literal name not in the catalog
  records a span no doc, waterfall legend or phase map knows
  (flagged at the call site);
* a catalog entry whose name is never recorded anywhere is a dead
  promise — the runbook describes a span that cannot occur (flagged
  at the catalog line).

Dynamic names (a variable first argument) are out of scope for the
literal scan; keep span names literal at call sites — that is what
makes traces greppable in the first place. The Horovod `Timeline`'s
``begin_span`` method is untouched: it is reached through a timeline
handle, never through a spans-module alias.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from horovod_tpu.analysis.core import Finding, RuleMeta, const_str

RULE = RuleMeta(
    id="HVD012",
    name="span-catalog-drift",
    severity="error",
    doc="spans.begin_span()/record_span() with a literal name not "
        "declared in obs/spans.py SPAN_CATALOG (undocumented span, "
        "invisible to phase anatomy), or a catalog entry whose name "
        "is never recorded (dead promise).")

_SPANS_MODULE = "obs/spans.py"
_SPANS_DOTTED = "horovod_tpu.obs.spans"
_RECORD_FNS = ("begin_span", "record_span")


def _spans_module(project):
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_SPANS_MODULE):
            return mi
    return None


def _catalog_from_tree(tree) -> Dict[str, int]:
    """{name: lineno} from the ``SPAN_CATALOG = {...}`` literal."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            tgts = [t.id for t in node.targets
                    if isinstance(t, ast.Name)]
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)):
            tgts = [node.target.id]
        else:
            continue
        if "SPAN_CATALOG" not in tgts:
            continue
        if isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                k = const_str(key) if key is not None else None
                if k:
                    out[k] = key.lineno
    return out


def _live_catalog() -> Dict[str, int]:
    try:
        from horovod_tpu.obs import spans as _sp
        return {k: 0 for k in getattr(_sp, "SPAN_CATALOG", {})}
    except ImportError:    # analyzing a foreign tree — static only
        return {}


def _span_aliases(mi) -> Tuple[Set[str], Set[str]]:
    """(module aliases of obs.spans, direct names bound to its
    ``begin_span``/``record_span``) — scanned over the WHOLE tree,
    because subsystems import the spans module function-locally."""
    mods: Set[str] = set()
    fns: Set[str] = set()
    for node in ast.walk(mi.src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _SPANS_DOTTED and alias.asname:
                    mods.add(alias.asname)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                if (mod.endswith("obs") and alias.name == "spans"):
                    mods.add(local)
                elif (mod.endswith("obs.spans")
                      and alias.name in _RECORD_FNS):
                    fns.add(local)
    return mods, fns


def record_sites(project) -> List[Tuple[str, int, int, str]]:
    """[(path, line, col, name)] — every literal-name begin/record
    through a spans-module alias, outside obs/spans.py itself."""
    out = []
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_SPANS_MODULE):
            continue
        mods, fns = _span_aliases(mi)
        if not mods and not fns:
            continue
        for node in ast.walk(mi.src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            hit = ((isinstance(fn, ast.Attribute)
                    and fn.attr in _RECORD_FNS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in mods)
                   or (isinstance(fn, ast.Name) and fn.id in fns))
            if not hit:
                continue
            name = const_str(node.args[0])
            if name:
                out.append((mi.path, node.lineno, node.col_offset,
                            name))
    return out


def check(project):
    sp_mi = _spans_module(project)
    if sp_mi is not None:
        catalog = _catalog_from_tree(sp_mi.src.tree)
    else:
        catalog = _live_catalog()

    sites = record_sites(project)
    for path, line, col, name in sites:
        if name in catalog:
            continue
        yield Finding(
            RULE.id, RULE.severity, path, line, col,
            f"span name {name!r} recorded but not declared in "
            f"SPAN_CATALOG (horovod_tpu/obs/spans.py) — undeclared "
            f"spans never reach the docs/observability.md span table "
            f"and the phase anatomy cannot attribute them")

    # Dead-promise direction only when the spans module itself is in
    # the analyzed set — a subtree run without the recorders would
    # call every entry dead.
    if sp_mi is None:
        return
    recorded = {name for (_, _, _, name) in sites}
    for name in sorted(catalog):
        if name not in recorded:
            yield Finding(
                RULE.id, RULE.severity, sp_mi.path, catalog[name], 0,
                f"SPAN_CATALOG entry {name!r} is never recorded by "
                f"any subsystem — dead promise in the operator docs; "
                f"record it or delete the entry")
