"""HVD008: cross-thread shared state with no common lock.

The static generalization of HVD004's single-class check: HVD004 asks
"is this attribute's lock discipline *consistent*?"; this rule asks
"do two *different threads* touch this attribute without a common
lock?". Thread entry points are every ``threading.Thread(target=...)``
target the resolver can see plus every ``@thread_entry``-annotated
function (`horovod_tpu.annotations`). From each entry the rule walks
the precisely-resolved call graph, recording every ``self.<attr>``
access (reads, writes, mutating container calls) together with the
set of locks lexically held at the access. An attribute WRITTEN on
one thread's reachable paths and read or written on a different
thread's, where some write/access pair shares **no** lock, is a data
race candidate and is flagged at the unguarded write.

``__init__`` is exempt (construction happens-before the thread
start), lock attributes themselves are exempt, and only classes that
own at least one lock are examined — a lock-free class is
single-threaded by design and HVD004 already covers the rest.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from horovod_tpu.analysis.core import Finding, RuleMeta
from horovod_tpu.analysis.rules._threads import (
    MUTATORS, local_class_types, sync_attrs, thread_world,
    walk_with_locks,
)

RULE = RuleMeta(
    id="HVD008",
    name="cross-thread-race",
    severity="warning",
    doc="Attribute written on one thread entry point's reachable "
        "paths and read/written on another's with no common lock "
        "held at both sites — a cross-thread data race candidate.")


def _self_attr(node: ast.AST):
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# access: (entry qname, fn qname, path, line, kind, frozenset(locks))
Access = Tuple[str, str, str, int, str, frozenset]


def _entry_accesses(world, entry_q, entry) -> List[Tuple[str, str,
                                                         Access]]:
    """Every ``self.<attr>`` access on ``entry``'s reachable paths,
    with the locks held at the access — held context PROPAGATES
    through precisely-resolved calls (a ``_locked``-suffix helper
    reached only from under the lock is guarded; the same helper
    reached bare from another entry is not). Closures are walked at
    their call sites via `walk_with_locks`; lock attrs and
    internally-synchronized attrs (threading.Event & co — thread-safe
    by their own contract) are exempt."""
    out: List[Tuple[str, str, Access]] = []
    seen: Set[Tuple[str, frozenset]] = set()

    def walk_fn(fi, entry_held):
        key = (fi.qname, frozenset(entry_held))
        if key in seen:
            return
        seen.add(key)
        mi = world.project.symbols.modules[fi.module]
        local_types = local_class_types(fi.node, mi,
                                        world.project.symbols)
        aliases = world.lock_aliases(fi, local_types)
        recording = fi.cls is not None and fi.name != "__init__"
        if recording:
            cls_q = f"{fi.module}:{fi.cls}"
            lock_attrs = set(world.locks_of.get(cls_q, ()))
            safe_attrs = sync_attrs(mi.classes[fi.cls])

        def record(attr, kind, node, held):
            if attr in lock_attrs or attr in safe_attrs:
                return
            out.append((cls_q, attr,
                        (entry_q, fi.qname, fi.src.path, node.lineno,
                         kind, frozenset(held))))

        def on_node(node, held):
            if recording:
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    tgts = (node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target])
                    for tgt in tgts:
                        for el in (tgt.elts
                                   if isinstance(tgt, ast.Tuple)
                                   else [tgt]):
                            attr = _self_attr(el)
                            if attr is not None:
                                record(attr, "write", el, held)
                if isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in MUTATORS):
                        attr = _self_attr(f.value)
                        if attr is not None:
                            record(attr, "write", node, held)
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    record(node.attr, "read", node, held)
            callees = []
            if isinstance(node, ast.Call):
                callees += world.resolve_precise(fi, node,
                                                 local_types)
            callees += world.protocol_callees(fi, node, local_types)
            for c in callees:
                walk_fn(c, tuple(sorted(set(held))))

        walk_with_locks(world, fi, aliases, local_types,
                        on_node=on_node, initial_held=entry_held)

    walk_fn(entry, ())
    return out


def check(project):
    world = thread_world(project)
    # (class qname, attr) -> [access]
    table: Dict[Tuple[str, str], List[Access]] = {}
    for entry_q in sorted(world.entries):
        entry, _how = world.entries[entry_q]
        for cls_q, attr, acc in _entry_accesses(world, entry_q,
                                                entry):
            if world.locks_of.get(cls_q):
                table.setdefault((cls_q, attr), []).append(acc)

    seen_sites = set()
    for (cls_q, attr) in sorted(table):
        accs = table[(cls_q, attr)]
        writes = [a for a in accs if a[4] == "write"]
        for w in sorted(writes, key=lambda a: (a[2], a[3])):
            racy = [a for a in accs
                    if a[0] != w[0] and not (a[5] & w[5])]
            if not racy:
                continue
            other = min(racy, key=lambda a: (a[2], a[3]))
            site = (w[2], w[3], attr)
            if site in seen_sites:
                continue
            seen_sites.add(site)
            cls_name = cls_q.split(":")[-1]
            held = (f" (holding {', '.join(sorted(w[5]))})"
                    if w[5] else " with no lock")
            yield Finding(
                RULE.id, RULE.severity, w[2], w[3], 0,
                f"self.{attr} of {cls_name} written on thread "
                f"{w[0].split(':')[-1]}{held} and "
                f"{other[4]} on thread {other[0].split(':')[-1]} at "
                f"{other[2]}:{other[3]} with no common lock — "
                f"cross-thread race candidate")
