"""HVD004: inconsistent lock discipline on shared attributes.

The serving engine, watchdog, and stall monitor synchronize by hand
(`threading.Lock` attributes + ``with self._lock:`` blocks). The bug
class that survives review is *mixed* discipline: an attribute
mutated under the lock in one method and bare in another — the bare
write races every reader that trusted the lock. For each class that
owns a lock attribute, this rule collects every mutation of every
``self.<attr>`` (assignments, augmented assignments, and mutating
container calls like ``.append()``/``.pop()``/``.clear()``), classes
them guarded/unguarded by lexical ``with self.<lock>`` enclosure, and
flags the unguarded sites of any attribute that is ALSO mutated under
the lock. ``__init__`` is exempt (construction happens-before
publication).

Single-owner attributes that a lock only brackets for a handoff
window (the scheduler's dispatch-thread containers) carry reasoned
suppressions — see docs/analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from horovod_tpu.analysis.core import Finding, RuleMeta, dotted_name

RULE = RuleMeta(
    id="HVD004",
    name="lock-discipline",
    severity="warning",
    doc="Attribute mutated both inside and outside `with self.<lock>` "
        "blocks across a class's methods — the unguarded writes race "
        "readers that trust the lock.")

_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_MUTATORS = {"append", "appendleft", "extend", "insert", "add",
             "remove", "discard", "clear", "pop", "popleft", "popitem",
             "update", "setdefault", "sort", "reverse"}


def _lock_attrs(ci) -> set:
    """self attributes assigned a threading.Lock/RLock/Condition in
    __init__."""
    init = ci.methods.get("__init__")
    if init is None:
        return set()
    from horovod_tpu.analysis.rules._threads import unwrap_lock_ctor
    out = set()
    for node in ast.walk(init.node):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        fn = dotted_name(node.value.func) or ""
        if (fn.split(".")[-1] not in _LOCK_TYPES
                and unwrap_lock_ctor(node.value) is None):
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                out.add(tgt.attr)
    return out


def _self_attr_of(node: ast.AST):
    """The X of a self.X[...]... target/receiver chain, else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutations(method, locks) -> List[Tuple[str, bool, ast.AST, str]]:
    """[(attr, guarded, node, how)] for one method body; `guarded` is
    lexical enclosure in `with self.<lock>:` for any class lock —
    including locks first bound to a local (``lock = self._lock;
    with lock:``)."""
    out = []
    aliases = set()
    for node in ast.walk(method.node):
        if (isinstance(node, ast.Assign)
                and _self_attr_of(node.value) in locks):
            aliases |= {t.id for t in node.targets
                        if isinstance(t, ast.Name)}

    def _holds_lock(expr) -> bool:
        if _self_attr_of(expr) in locks:
            return True
        return isinstance(expr, ast.Name) and expr.id in aliases

    def visit(node, guarded):
        if isinstance(node, ast.With):
            holds = any(
                _holds_lock(item.context_expr)
                for item in node.items)
            for child in node.body:
                visit(child, guarded or holds)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested def/lambda (a gauge set_fn closure, a sort
            # key) runs at CALL time: the enclosing `with` is NOT
            # held then, so its mutations are analyzed as unguarded
            # rather than skipped (the pre-fix blind spot).
            body = (node.body if isinstance(node.body, list)
                    else [node.body])
            for child in body:
                visit(child, False)
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = (node.targets if isinstance(node, ast.Assign)
                    else [node.target])
            for tgt in tgts:
                elts = (tgt.elts if isinstance(tgt, ast.Tuple)
                        else [tgt])
                for el in elts:
                    attr = _self_attr_of(el)
                    if attr is not None and attr not in locks:
                        out.append((attr, guarded, el, "assignment"))
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATORS):
                attr = _self_attr_of(fn.value)
                if attr is not None and attr not in locks:
                    out.append((attr, guarded, node,
                                f".{fn.attr}() call"))
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    for stmt in method.node.body:
        visit(stmt, False)
    return out


def check(project):
    table = project.symbols
    for mi in table.modules.values():
        for ci in mi.classes.values():
            locks = _lock_attrs(ci)
            if not locks:
                continue
            per_attr: Dict[str, Dict[bool, list]] = {}
            for mname, method in ci.methods.items():
                if mname == "__init__":
                    continue
                for attr, guarded, node, how in _mutations(method,
                                                           locks):
                    per_attr.setdefault(attr, {True: [], False: []})[
                        guarded].append((node, how, mname))
            for attr in sorted(per_attr):
                sites = per_attr[attr]
                if not sites[True] or not sites[False]:
                    continue   # consistent discipline (or lock-free)
                for node, how, mname in sites[False]:
                    yield Finding(
                        RULE.id, RULE.severity, ci.src.path,
                        node.lineno, node.col_offset,
                        f"self.{attr} mutated without the lock in "
                        f"{ci.name}.{mname} ({how}) but under "
                        f"`with self.<lock>` elsewhere in the class "
                        f"— unguarded writes race lock-trusting "
                        f"readers")
