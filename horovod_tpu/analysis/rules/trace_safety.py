"""HVD002: Python control flow on traced values inside compiled code.

Inside a ``jax.jit`` / ``vmap`` / ``shard_map``-compiled function the
arguments are tracers: a Python ``if``/``while``/``assert`` (or a
ternary) on a value *derived from a traced parameter* either raises
``TracerBoolConversionError`` at trace time or — worse, when the
branch happens to be constant-foldable — silently bakes one branch
into the compiled program. The repo's compiled functions keep control
flow in ``lax.cond`` / ``jnp.where`` / masks; this rule keeps it that
way.

Static structure is fine and NOT flagged: tests on ``x.shape`` /
``x.ndim`` / ``x.dtype`` / ``len(x)``, ``is None`` / ``is not None``
comparisons, ``isinstance``, and parameters named in
``static_argnames`` / ``static_argnums``.
"""

from __future__ import annotations

import ast

from horovod_tpu.analysis.core import (Finding, RuleMeta, dotted_name,
                                       walk_scope)
from horovod_tpu.analysis.symbols import (JIT_NAMES, FunctionInfo,
                                          _static_params)

RULE = RuleMeta(
    id="HVD002",
    name="trace-unsafe-control-flow",
    severity="error",
    doc="Python if/while/assert on a traced value inside a "
        "jit/vmap/shard_map-compiled function fails (or silently "
        "specializes) at trace time.")

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance", "callable", "hasattr", "getattr",
                 "type"}


_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _taint(scope_node, names: set) -> set:
    """Extend ``names`` with locals derived from them by plain
    assignment within this scope — but an assignment that touches
    traced names only through static structure (``n = x.shape[0]``,
    ``d = x.dtype``) binds a PYTHON value, not a tracer, and must not
    taint (same benign set the test check below uses)."""
    names = set(names)
    changed = True
    while changed:
        changed = False
        for node in walk_scope(scope_node):
            if isinstance(node, ast.Assign):
                if not _offending_names(node.value, names):
                    continue
                for tgt in node.targets:
                    elts = (tgt.elts if isinstance(tgt, ast.Tuple)
                            else [tgt])
                    for el in elts:
                        if (isinstance(el, ast.Name)
                                and el.id not in names):
                            names.add(el.id)
                            changed = True
    return names


def _offending_names(test: ast.AST, traced: set) -> set:
    """Traced names referenced by ``test`` in a value position (not
    under a static attribute / len / is-None comparison)."""
    bad = set()

    def visit(node, benign=False):
        if isinstance(node, ast.Name):
            if node.id in traced and not benign:
                bad.add(node.id)
            return
        if isinstance(node, ast.Attribute):
            # x.shape / x.ndim / ... : the whole chain is static.
            visit(node.value, benign=benign
                  or node.attr in _STATIC_ATTRS)
            return
        if isinstance(node, ast.Call):
            fn = node.func
            static_call = (isinstance(fn, ast.Name)
                           and fn.id in _STATIC_CALLS)
            for child in ast.iter_child_nodes(node):
                visit(child, benign=benign or static_call)
            return
        if isinstance(node, ast.Compare):
            ops_none = all(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators)
            for child in ast.iter_child_nodes(node):
                visit(child, benign=benign or ops_none)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, benign=benign)

    visit(test)
    return bad


def _nested_traced_params(fi, nested, traced) -> set:
    """Which of a nested def's params are tracers. A nested def handed
    to a combinator (``lax.scan(body, ...)`` — its NAME referenced as
    an argument) receives tracers on every param; one only ever CALLED
    directly (``helper(3)``) receives whatever each call site passes,
    so taint params positionally from the direct calls instead of
    blanket-marking them (a static ``helper(n)`` branch is
    trace-safe). Lambdas and un-referenced defs stay conservative."""
    if isinstance(nested, ast.Lambda):
        return {p.arg for p in nested.args.args}
    params = [p.arg for p in nested.args.args]
    direct_calls = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Name)
                and node.func.id == nested.name):
            direct_calls.append(node)
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if any(isinstance(n, ast.Name) and n.id == nested.name
                   for n in ast.walk(arg)):
                return set(params)      # passed as a callback
    if not direct_calls:
        return set(params)              # never referenced: stay safe
    out = set()
    for call in direct_calls:
        for idx, arg in enumerate(call.args):
            if idx < len(params) and _offending_names(arg, traced):
                out.add(params[idx])
        for kw in call.keywords:
            if kw.arg in params and _offending_names(kw.value, traced):
                out.add(kw.arg)
    return out


def _scan_scope(fi, scope_node, traced):
    """Flag control flow on ``traced`` within one scope, then recurse
    into nested defs/lambdas with THEIR traced params added — a nested
    body closes over tracers and runs under the trace (vmapped/scanned
    bodies), but its param names must NOT leak into the enclosing
    scope, where an unrelated static local may share the name."""
    traced = _taint(scope_node, traced)
    for node in walk_scope(scope_node):
        if isinstance(node, (ast.If, ast.While)):
            test, kind = node.test, type(node).__name__.lower()
        elif isinstance(node, ast.Assert):
            test, kind = node.test, "assert"
        elif isinstance(node, ast.IfExp):
            test, kind = node.test, "ternary"
        else:
            continue
        bad = _offending_names(test, traced)
        if bad:
            yield Finding(
                RULE.id, RULE.severity, fi.src.path, node.lineno,
                node.col_offset,
                f"Python {kind} on traced value(s) "
                f"{', '.join(sorted(bad))} inside "
                f"{fi.jit_kind}-compiled "
                f"{fi.qname.split(':')[1]} — use lax.cond / "
                f"jnp.where or mark the argument static")
    for node in walk_scope(scope_node):
        if isinstance(node, _SCOPES):
            # Params SHADOW closure names: drop them from the outer
            # set before adding the ones that actually carry tracers.
            params = {p.arg for p in node.args.args}
            inner = ((traced - params)
                     | _nested_traced_params(fi, node, traced))
            yield from _scan_scope(fi, node, inner)


def _local_jit_defs(fi):
    """Nested defs jit-compiled inside a NON-jit function body —
    ``step = jax.jit(step)`` / ``jax.jit(step, ...)(x)`` — run traced
    exactly like decorated ones (the repo's factory functions build
    their train/eval steps this way). Yields a FunctionInfo view per
    wrapped def, with statics taken from the jit call's keywords."""
    defs = {n.name: n for n in ast.walk(fi.node)
            if isinstance(n, ast.FunctionDef) and n is not fi.node}
    seen = set()
    for node in ast.walk(fi.node):
        if (isinstance(node, ast.Call)
                and dotted_name(node.func) in JIT_NAMES
                and node.args and isinstance(node.args[0], ast.Name)
                and node.args[0].id in defs
                and node.args[0].id not in seen):
            seen.add(node.args[0].id)
            inner = defs[node.args[0].id]
            pseudo = FunctionInfo(fi.module, inner.name, fi.cls,
                                  inner, fi.src)
            pseudo.jit_kind = pseudo.jit_kind or "jit"
            kwargs = {kw.arg: kw.value for kw in node.keywords
                      if kw.arg}
            pseudo.static_params |= _static_params(inner, kwargs)
            yield pseudo


def check(project):
    for fi in project.symbols.all_functions():
        # The symbol table marks module-level `f = jax.jit(g)` targets
        # with jit_kind, so alias-wrapped functions land here too.
        targets = ([fi] if fi.jit_kind is not None
                   else _local_jit_defs(fi))
        for t in targets:
            seed = set(t.param_names()) - t.static_params - {"self"}
            yield from _scan_scope(t, t.node, seed)
