"""HVD003: recompilation hazards at jit call sites.

XLA compiles are the silent regression TPU-pod papers keep
rediscovering (Scale MLPerf-0.6, arXiv:1909.09756): a program that
retraces per step is 10-100x slower and looks healthy. Three
statically visible hazard shapes:

* **jit-and-discard** — ``jax.jit(f)(x)`` inside a function body: the
  wrapper (and its compile cache entry's home) dies with the call, so
  every invocation of the enclosing function retraces. Hoist the
  wrapper to module scope or cache it. (One-shot setup/probe sites
  carry a reasoned suppression.)
* **varying Python scalar** — a loop-variable (or arithmetic on one)
  passed as a traced argument to a known jit-compiled function: every
  distinct Python scalar is a new constant in the trace => a new
  compile per iteration. Pass it as a device array (``jnp.int32(i)``)
  or mark it static deliberately.
* **non-hashable static** — a list/dict/set literal passed for a
  ``static_argnames``/``static_argnums`` parameter raises
  ``TypeError: unhashable`` at call time.
"""

from __future__ import annotations

import ast

from horovod_tpu.analysis.core import Finding, RuleMeta, dotted_name
from horovod_tpu.analysis.symbols import JIT_NAMES

RULE = RuleMeta(
    id="HVD003",
    name="recompilation-hazard",
    severity="warning",
    doc="jit call sites that retrace per call: discarded jit "
        "wrappers, loop-varying Python scalars, non-hashable static "
        "arguments.")

def _loop_vars(fn_node) -> dict:
    """{name: for-node} for loop targets iterating range/enumerate
    within this function scope."""
    out = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.For):
            it = node.iter
            fn = (dotted_name(it.func)
                  if isinstance(it, ast.Call) else None)
            if fn not in ("range", "enumerate"):
                continue
            tgts = (node.target.elts
                    if isinstance(node.target, ast.Tuple)
                    else [node.target])
            for t in tgts:
                if isinstance(t, ast.Name):
                    out[t.id] = node
    return out


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _inside(loop: ast.For, node) -> bool:
    """Lexically within the loop body — a use AFTER the loop sees one
    final value and compiles once, which is not a hazard."""
    return (loop.lineno <= node.lineno
            <= (loop.end_lineno or loop.lineno))


def _is_scalar_expr(node) -> bool:
    """Bare name or arithmetic over names/constants — the shapes that
    smuggle a varying Python scalar into a trace. A Call (e.g.
    ``jnp.int32(i)``) is a conversion and passes."""
    if isinstance(node, ast.Name):
        return True
    if isinstance(node, ast.BinOp):
        return _is_scalar_expr(node.left) and _is_scalar_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_scalar_expr(node.operand)
    if isinstance(node, ast.Constant):
        return True
    return False


def check(project):
    table = project.symbols
    for fi in table.all_functions():
        mi = table.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None
        loops = _loop_vars(fi.node)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            # (a) jit-and-discard: jax.jit(f)(...) in a function body.
            if (isinstance(node.func, ast.Call)
                    and dotted_name(node.func.func) in JIT_NAMES):
                yield Finding(
                    RULE.id, RULE.severity, fi.src.path, node.lineno,
                    node.col_offset,
                    f"jit wrapper created and discarded per call of "
                    f"{fi.qname.split(':')[1]} — every invocation "
                    f"retraces; hoist jax.jit to module scope or "
                    f"cache the wrapper")
                continue
            callees = table.resolve_call(mi, ci, node)
            callee = callees[0] if callees else None
            if not table.is_jit_callee(callee, mi, node):
                continue
            static = callee.static_params if callee else set()
            params = callee.param_names() if callee else []
            for idx, arg in enumerate(node.args):
                pname = params[idx] if idx < len(params) else None
                # (c) non-hashable static argument.
                if pname in static and isinstance(
                        arg, (ast.List, ast.Dict, ast.Set)):
                    yield Finding(
                        RULE.id, RULE.severity, fi.src.path,
                        arg.lineno, arg.col_offset,
                        f"non-hashable {type(arg).__name__.lower()} "
                        f"literal passed for static parameter "
                        f"{pname!r} of {callee.name} — jit static "
                        f"args must be hashable")
                    continue
                if pname in static:
                    continue
                # (b) loop-varying Python scalar as traced arg.
                hot = {v for v in _names_in(arg) & loops.keys()
                       if _inside(loops[v], node)}
                if _is_scalar_expr(arg) and hot:
                    var = sorted(hot)[0]
                    yield Finding(
                        RULE.id, RULE.severity, fi.src.path,
                        arg.lineno, arg.col_offset,
                        f"loop variable {var!r} passed as a traced "
                        f"Python scalar to jit-compiled "
                        f"{getattr(callee, 'name', dotted_name(node.func))}"
                        f" — each iteration recompiles; wrap it "
                        f"(jnp.int32(...)) or mark it static")
