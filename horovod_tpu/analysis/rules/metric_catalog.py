"""HVD010: metric names drifting from the obs/catalog.py contract.

`obs/catalog.py` is the single declaration site for every metric
family (names, kinds, labels, docs) — the exporter pre-declares from
it and docs/observability.md's table is generated prose of it. Two
drift directions break that contract:

* a subsystem calling ``reg.counter/gauge/histogram("name", ...)``
  with a literal name **not** declared in the catalog creates a
  family the docs/dashboards never heard of (flagged at the call);
* a catalog entry whose dict key is never fetched anywhere
  (``..._metrics()["key"]`` / a key subscript on a stored family
  dict) is a dead declaration — scrapes show a family no code can
  ever move (flagged at the declaration).

Dynamic names (f-strings, derived names in the fleet aggregator) are
invisible to a literal scan and are out of scope by design — the
catalog contract is about the *static* vocabulary.
"""

from __future__ import annotations

import ast
from typing import Dict, Set, Tuple

from horovod_tpu.analysis.core import (
    Finding, RuleMeta, const_str, dotted_name,
)

RULE = RuleMeta(
    id="HVD010",
    name="metric-catalog-drift",
    severity="error",
    doc="Metric constructed through the registry with a literal name "
        "not declared in obs/catalog.py, or a catalog entry whose "
        "key is never fetched by any subsystem (dead declaration).")

_CATALOG = "obs/catalog.py"
_REGISTRY = "obs/registry.py"
_CTORS = {"counter", "gauge", "histogram"}


def _catalog_module(project):
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_CATALOG):
            return mi
    return None


def _catalog_entries(tree) -> Dict[str, Tuple[str, int]]:
    """{metric name: (dict key, lineno)} from the catalog's
    ``"key": reg.counter("name", ...)`` declaration dicts."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            k = const_str(key) if key is not None else None
            if k is None or not isinstance(value, ast.Call):
                continue
            fn = dotted_name(value.func) or ""
            if fn.split(".")[-1] not in _CTORS or not value.args:
                continue
            name = const_str(value.args[0])
            if name:
                out[name] = (k, value.args[0].lineno)
    return out


def _used_keys(project) -> Set[str]:
    """Every string literal outside the catalog — the conservative
    'somebody fetches this entry' evidence. Catalog keys reach their
    fetch sites through indirection this scan cannot chase
    (``self._m[name].inc()`` behind ``self._count("retries")``, the
    ``name in ("prefix_hits", ...)`` dispatch in serving/metrics.py),
    so presence of the key string ANYWHERE else is the only
    false-positive-free liveness signal; a key string that occurs
    nowhere else is certainly dead."""
    out: Set[str] = set()
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_CATALOG):
            continue
        for node in ast.walk(mi.src.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                out.add(node.value)
    return out


def _live_catalog_names() -> Set[str]:
    """Declared names harvested from the INSTALLED catalog's source —
    the subtree/fixture-run fallback (mirrors HVD005's live-registry
    fallback) so real metric names don't produce phantom findings when
    obs/catalog.py is not in the analyzed file set."""
    try:
        from horovod_tpu.obs import catalog as _cat
        with open(_cat.__file__) as fh:
            tree = ast.parse(fh.read())
    except (ImportError, OSError, SyntaxError):
        return set()    # analyzing a foreign tree — static only
    return set(_catalog_entries(tree))


def check(project):
    cat_mi = _catalog_module(project)
    if cat_mi is None:
        entries = {}
        declared = _live_catalog_names()
    else:
        entries = _catalog_entries(cat_mi.src.tree)
        declared = set(entries)

    for mi in project.symbols.modules.values():
        if mi.path.endswith((_CATALOG, _REGISTRY)):
            continue
        for node in ast.walk(mi.src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _CTORS and node.args):
                continue
            name = const_str(node.args[0])
            if name is None or name in declared:
                continue
            yield Finding(
                RULE.id, RULE.severity, mi.path, node.lineno,
                node.col_offset,
                f"metric {name!r} constructed via .{fn.attr}() but "
                f"not declared in horovod_tpu/obs/catalog.py — "
                f"undeclared families are invisible to the exporter "
                f"pre-declaration and the generated docs table")

    # Dead-entry direction only when the catalog itself is in the
    # analyzed set — a subtree run without the consumers would call
    # every entry dead.
    if cat_mi is None:
        return
    used = _used_keys(project)
    for name in sorted(entries):
        key, line = entries[name]
        if key not in used:
            yield Finding(
                RULE.id, RULE.severity, cat_mi.path, line, 0,
                f"catalog entry {name!r} (key {key!r}) is never "
                f"fetched by any subsystem — dead declaration; "
                f"wire it up or delete it")
