"""Shared concurrency-analysis machinery for HVD007/008/009.

The three concurrency rules see the same world: which attributes hold
locks, which attributes hold *objects of analyzed classes* (so a call
through ``self.scheduler.step()`` resolves to the real method instead
of a name union), which functions are thread entry points, and which
lock a ``with`` item names. That world is built here, once, and cached
on the `Project`.

Lock identity is a *node name*: ``ClassName.attr`` for instance locks
(``self._lock = threading.Lock()`` in ``__init__``) and
``modstem.NAME`` for module-level locks. The runtime witness
(`horovod_tpu.analysis.lockcheck`) registers locks under the same
names, which is what lets a test diff the static HVD007 graph against
the dynamically observed one. A lock constructed through the witness
wrapper — ``lockcheck.register("Engine._lock", threading.Lock())`` —
is still recognized as a lock by every rule here (and by HVD004).

Call resolution is deliberately *precise, not complete*: self-methods,
module functions, imported functions, constructors, and attr-typed
receivers (``self.pool.allocate()`` where ``self.pool = BlockPool()``)
resolve; an unknown receiver resolves to nothing. The name-union
fallback `symbols.resolve_call` uses for HVD001 reachability would
manufacture lock-graph cycles and phantom cross-thread accesses out of
coincidental method names — for these rules under-approximating calls
is the safe direction (a missed edge is a missed finding; an invented
edge is a false deadlock report).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from horovod_tpu.analysis.core import dotted_name

LOCK_TYPES = {"Lock", "RLock", "Condition"}

#: Mutating container-method names (shared with HVD004's idea of a
#: write): calling one of these on ``self.X`` mutates ``self.X``.
MUTATORS = {"append", "appendleft", "extend", "insert", "add",
            "remove", "discard", "clear", "pop", "popleft", "popitem",
            "update", "setdefault", "sort", "reverse"}

_THREAD_CTORS = {"threading.Thread", "Thread"}

#: Internally-synchronized constructor leaves: mutator-shaped calls on
#: an attribute holding one of these (``self._stop.clear()``,
#: ``self._wake.set()``) are thread-safe by the object's own contract,
#: not shared-state writes — HVD008 exempts them.
SYNC_TYPES = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
              "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}


def unwrap_lock_ctor(value: ast.AST) -> Optional[str]:
    """If ``value`` constructs a lock, return the witness name it was
    registered under ('' when unwrapped/unnamed), else None. Sees
    through the runtime witness: ``lockcheck.register(name, Lock())``
    is a lock construction with an explicit name."""
    if not isinstance(value, ast.Call):
        return None
    fn = dotted_name(value.func) or ""
    leaf = fn.split(".")[-1]
    if leaf in LOCK_TYPES:
        return ""
    if leaf == "register" and len(value.args) == 2:
        inner = unwrap_lock_ctor(value.args[1])
        if inner is not None:
            name = value.args[0]
            if (isinstance(name, ast.Constant)
                    and isinstance(name.value, str)):
                return name.value
            return ""
    return None


def class_lock_attrs(ci) -> Dict[str, str]:
    """{attr: witness-name-or-''} for self attributes assigned a lock
    in ``__init__`` (construction happens-before publication, so
    ``__init__`` is where a lock is born)."""
    init = ci.methods.get("__init__")
    out: Dict[str, str] = {}
    if init is None:
        return out
    for node in ast.walk(init.node):
        if not isinstance(node, ast.Assign):
            continue
        wname = unwrap_lock_ctor(node.value)
        if wname is None:
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                out[tgt.attr] = wname
    return out


def module_lock_names(mi) -> Dict[str, str]:
    """{global-name: node-name} for module-level lock assignments."""
    stem = mi.path.rsplit("/", 1)[-1][:-3]
    out: Dict[str, str] = {}
    for node in mi.src.tree.body:
        if isinstance(node, ast.Assign):
            if unwrap_lock_ctor(node.value) is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = f"{stem}.{tgt.id}"
    return out


def sync_attrs(ci) -> Set[str]:
    """self attributes assigned a `SYNC_TYPES` object anywhere in the
    class — receivers whose methods synchronize internally."""
    out: Set[str] = set()
    for method in ci.methods.values():
        for node in ast.walk(method.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            leaf = (dotted_name(node.value.func) or "").split(".")[-1]
            if leaf not in SYNC_TYPES:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out.add(tgt.attr)
    return out


def local_closures(fn_node) -> Dict[str, ast.AST]:
    """{name: def-node} for functions nested (at any depth) inside
    ``fn_node``'s scope — the call-site held-lock modeling targets."""
    out: Dict[str, ast.AST] = {}

    def scan(scope):
        for child in ast.iter_child_nodes(scope):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                out.setdefault(child.name, child)
                scan(child)
            elif not isinstance(child, (ast.Lambda, ast.ClassDef)):
                scan(child)

    scan(fn_node)
    return out


def _resolve_class(mi, table, name: Optional[str]):
    """A dotted constructor name -> ClassInfo in the analyzed set."""
    if not name:
        return None
    if name in mi.classes:
        return mi.classes[name]
    if name in mi.from_imports:
        mod_dotted, orig = mi.from_imports[name]
        target = table.module_by_dotted(mod_dotted)
        if target is not None:
            return target.classes.get(orig)
    if "." in name:
        base, leaf = name.rsplit(".", 1)
        dotted = mi.module_aliases.get(base)
        if dotted is not None:
            target = table.module_by_dotted(dotted)
            if target is not None:
                return target.classes.get(leaf)
    return None


def attr_types(ci, table) -> Dict[str, object]:
    """{attr: ClassInfo} for ``self.X = SomeAnalyzedClass(...)``
    assignments anywhere in the class — the receiver-type map that
    lets ``self.X.method()`` resolve cross-object. An attr assigned
    two different analyzed classes keeps the first (sorted by method
    name) — ambiguity is rare and either choice is a sound witness."""
    mi = table.modules[ci.module]
    out: Dict[str, object] = {}
    for mname in sorted(ci.methods):
        method = ci.methods[mname]
        for node in ast.walk(method.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            target_cls = _resolve_class(
                mi, table, dotted_name(node.value.func))
            if target_cls is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out.setdefault(tgt.attr, target_cls)
    return out


def local_class_types(fn_node, mi, table) -> Dict[str, object]:
    """{local-var: ClassInfo} for ``v = SomeAnalyzedClass(...)``
    bindings in one function body (nested defs excluded)."""
    from horovod_tpu.analysis.core import walk_scope
    out: Dict[str, object] = {}
    for node in walk_scope(fn_node):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        target_cls = _resolve_class(
            mi, table, dotted_name(node.value.func))
        if target_cls is None:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.setdefault(tgt.id, target_cls)
    return out


class ThreadWorld:
    """The per-project concurrency model, built once and cached."""

    def __init__(self, project):
        self.project = project
        table = project.symbols
        # class qname ("path:Class") -> {lockattr: witness name}
        self.locks_of: Dict[str, Dict[str, str]] = {}
        # class qname -> {attr: ClassInfo}
        self.types_of: Dict[str, Dict[str, object]] = {}
        # module path -> {global: node-name}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        for mi in table.modules.values():
            if mi.path.endswith("analysis/lockcheck.py"):
                # The runtime witness's own mutex guards the recorder,
                # not product state — it must not become a graph node
                # (the witness never records itself either).
                continue
            self.module_locks[mi.path] = module_lock_names(mi)
            for ci in mi.classes.values():
                q = f"{mi.path}:{ci.name}"
                self.locks_of[q] = class_lock_attrs(ci)
                self.types_of[q] = attr_types(ci, table)
        self.entries = self._thread_entries()

    # -- thread entry points ------------------------------------------

    def _thread_entries(self) -> Dict[str, Tuple[object, str]]:
        """{qname: (FunctionInfo, how)} — every function handed to
        ``threading.Thread(target=...)`` plus every ``@thread_entry``
        annotation (the escape hatch for targets resolution can't
        see: callables built dynamically, callbacks invoked by
        foreign threads)."""
        table = self.project.symbols
        out: Dict[str, Tuple[object, str]] = {}
        for fi in table.all_functions():
            for dec in fi.node.decorator_list:
                if (dotted_name(dec) or "").split(".")[-1] == \
                        "thread_entry":
                    out[fi.qname] = (fi, "@thread_entry")
        for mi in table.modules.values():
            for ci in list(mi.classes.values()) + [None]:
                methods = (ci.methods.values() if ci
                           else mi.functions.values())
                for method in methods:
                    for node in ast.walk(method.node):
                        if not isinstance(node, ast.Call):
                            continue
                        if dotted_name(node.func) not in _THREAD_CTORS:
                            continue
                        tgt = self._thread_target(node, mi, ci)
                        if tgt is not None:
                            out.setdefault(
                                tgt.qname,
                                (tgt, f"Thread target at "
                                      f"{mi.path}:{node.lineno}"))
        return out

    def _thread_target(self, call: ast.Call, mi, ci):
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and call.args:
            target = call.args[0]
        if target is None:
            return None
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and ci is not None):
            return ci.methods.get(target.attr)
        if isinstance(target, ast.Name):
            f = mi.functions.get(target.id)
            if f is not None:
                return f
            if target.id in mi.from_imports:
                mod_dotted, orig = mi.from_imports[target.id]
                tm = self.project.symbols.module_by_dotted(mod_dotted)
                if tm is not None:
                    return tm.functions.get(orig)
        return None

    # -- precise call resolution --------------------------------------

    def resolve_precise(self, fi, call: ast.Call,
                        local_types: Dict[str, object]) -> List:
        """Callees of ``call`` inside ``fi`` — precise paths only (no
        method-name union; see module docstring)."""
        table = self.project.symbols
        mi = table.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None
        fn = call.func
        if isinstance(fn, ast.Name):
            f = mi.functions.get(fn.id)
            if f is not None:
                return [f]
            if fn.id in mi.from_imports:
                mod_dotted, orig = mi.from_imports[fn.id]
                target = table.module_by_dotted(mod_dotted)
                if target is not None:
                    f = target.functions.get(orig)
                    if f is not None:
                        return [f]
                    c = target.classes.get(orig)
                    if c is not None and "__init__" in c.methods:
                        return [c.methods["__init__"]]
            c = mi.classes.get(fn.id)
            if c is not None and "__init__" in c.methods:
                return [c.methods["__init__"]]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        base = fn.value
        if isinstance(base, ast.Call):
            # Call-result receiver: ``get().emit(...)`` — typed by the
            # inner callee's return annotation (``def get() ->
            # EventLog``). Without this the static graph misses edges
            # the runtime witness observes through accessor functions.
            out = []
            for callee in self.resolve_precise(fi, base, local_types):
                cls = self._return_class(callee)
                if cls is not None:
                    m = cls.methods.get(fn.attr)
                    if m is not None:
                        out.append(m)
            return out
        if isinstance(base, ast.Name):
            if base.id == "self" and ci is not None:
                m = ci.methods.get(fn.attr)
                if m is not None:
                    return [m]
                # self.<attr-typed>.__call__ etc. fall through below
            cls = local_types.get(base.id)
            if cls is not None:
                m = cls.methods.get(fn.attr)
                return [m] if m is not None else []
            dotted = mi.module_aliases.get(base.id)
            if dotted is not None:
                target = table.module_by_dotted(dotted)
                if target is not None:
                    f = target.functions.get(fn.attr)
                    if f is not None:
                        return [f]
                    c = target.classes.get(fn.attr)
                    if c is not None and "__init__" in c.methods:
                        return [c.methods["__init__"]]
            return []
        # self.X.method() via the attr-type map
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and ci is not None):
            cls = self.types_of.get(
                f"{mi.path}:{ci.name}", {}).get(base.attr)
            if cls is not None:
                m = cls.methods.get(fn.attr)
                return [m] if m is not None else []
        return []

    def _return_class(self, callee):
        """The ClassInfo a function's return annotation names (plain
        or string-quoted), resolved in the CALLEE's module."""
        table = self.project.symbols
        mi = table.modules[callee.module]
        ann = getattr(callee.node, "returns", None)
        if ann is None:
            return None
        if (isinstance(ann, ast.Constant)
                and isinstance(ann.value, str)):
            name = ann.value.strip("'\"")
        else:
            name = dotted_name(ann)
        return _resolve_class(mi, table, name)

    def protocol_callees(self, fi, node: ast.AST,
                         local_types: Dict[str, object]) -> List:
        """Dunder-protocol calls the runtime makes but no ast.Call
        shows: ``len(self.X)`` -> ``X.__len__``, ``self.X[k]`` ->
        ``__getitem__``/``__setitem__``, ``k in self.X`` ->
        ``__contains__``, ``for _ in self.X`` -> ``__iter__`` — for
        attr-typed receivers only. Without these the static lock
        graph misses edges the runtime witness observes (e.g. an
        engine holding its lock while ``len(self.queue)`` takes the
        queue's)."""
        table = self.project.symbols
        mi = table.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None

        def recv_cls(expr):
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and ci is not None):
                return self.types_of.get(
                    f"{mi.path}:{ci.name}", {}).get(expr.attr)
            if isinstance(expr, ast.Name):
                return local_types.get(expr.id)
            return None

        out = []

        def add(cls, dunder):
            if cls is not None:
                m = cls.methods.get(dunder)
                if m is not None:
                    out.append(m)

        if (isinstance(node, ast.Call) and isinstance(node.func,
                                                      ast.Name)
                and node.func.id == "len" and node.args):
            add(recv_cls(node.args[0]), "__len__")
        elif isinstance(node, ast.Subscript):
            add(recv_cls(node.value),
                "__setitem__" if isinstance(node.ctx, ast.Store)
                else "__getitem__")
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    add(recv_cls(comp), "__contains__")
        elif isinstance(node, (ast.For, ast.comprehension)):
            add(recv_cls(node.iter), "__iter__")
        return out

    # -- lock-expression naming ---------------------------------------

    def lock_node(self, expr: ast.AST, fi,
                  local_aliases: Dict[str, str],
                  local_types: Dict[str, object]) -> Optional[str]:
        """The lock-graph node a ``with`` item (or receiver) names,
        else None. Resolves ``self.<lock>``, cross-object
        ``self.<obj>.<lock>`` / ``local.<lock>``, module-level locks,
        and locals aliasing any of those."""
        table = self.project.symbols
        mi = table.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None
        if isinstance(expr, ast.Name):
            if expr.id in local_aliases:
                return local_aliases[expr.id]
            return self.module_locks.get(mi.path, {}).get(expr.id)
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and ci is not None:
                q = f"{mi.path}:{ci.name}"
                if expr.attr in self.locks_of.get(q, {}):
                    return f"{ci.name}.{expr.attr}"
                return None
            cls = local_types.get(base.id)
            if cls is not None:
                q = f"{cls.module}:{cls.name}"
                if expr.attr in self.locks_of.get(q, {}):
                    return f"{cls.name}.{expr.attr}"
            return None
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and ci is not None):
            cls = self.types_of.get(
                f"{mi.path}:{ci.name}", {}).get(base.attr)
            if cls is not None:
                q = f"{cls.module}:{cls.name}"
                if expr.attr in self.locks_of.get(q, {}):
                    return f"{cls.name}.{expr.attr}"
        return None

    def lock_aliases(self, fi, local_types) -> Dict[str, str]:
        """{local: node-name} for ``lock = self._lock``-style rebinds
        in ``fi``'s own scope."""
        from horovod_tpu.analysis.core import walk_scope
        out: Dict[str, str] = {}
        for node in walk_scope(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            node_name = self.lock_node(node.value, fi, out,
                                       local_types)
            if node_name is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node_name
        return out


def walk_with_locks(world, fi, aliases, local_types,
                    on_acquire=None, on_node=None,
                    initial_held=()):
    """Drive a held-lock-tracking walk of ``fi``'s body — THE shared
    execution-context model for HVD007/008/009, so the three rules
    cannot disagree about what is held where.

    ``on_acquire(lock, expr, held)`` fires when a ``with`` item names
    a lock (``held`` = locks already held, in acquisition order);
    ``on_node(node, held)`` fires pre-order for every other node.

    Nested defs are NOT walked in place — a closure's body runs at
    CALL time, not where it is written. Instead each body is walked at
    every local call site with the call site's held set (this is what
    lets a helper defined above a ``with self._lock:`` block and
    invoked inside it count as guarded), and a closure never called
    locally (an escaping callback: a gauge set_fn, a Thread target) is
    walked once with nothing held. Lambdas are treated as escaping.

    ``initial_held`` seeds the held set — callers doing
    interprocedural propagation (HVD008 walking a ``_locked``-suffix
    helper from its guarded call site) pass the caller's held locks.
    """
    closures = local_closures(fi.node)
    called: Set[str] = set()
    active: Set[str] = set()

    def visit(node, held: Tuple[str, ...]):
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                ln = world.lock_node(item.context_expr, fi, aliases,
                                     local_types)
                if ln:
                    if on_acquire is not None:
                        on_acquire(ln, item.context_expr,
                                   tuple(inner))
                    inner.append(ln)
                else:
                    visit(item.context_expr, tuple(inner))
            for child in node.body:
                visit(child, tuple(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return    # walked at call sites / escape epilogue below
        if isinstance(node, ast.Lambda):
            visit(node.body, ())
            return
        if on_node is not None:
            on_node(node, held)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in closures
                and node.func.id not in active):
            name = node.func.id
            called.add(name)
            active.add(name)
            for child in closures[name].body:
                visit(child, held)
            active.discard(name)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fi.node.body:
        visit(stmt, tuple(initial_held))
    for name in sorted(closures):
        if name not in called:
            for child in closures[name].body:
                visit(child, ())


def thread_world(project) -> ThreadWorld:
    """Build (or fetch the cached) `ThreadWorld` for ``project``."""
    world = getattr(project, "_thread_world", None)
    if world is None:
        world = ThreadWorld(project)
        project._thread_world = world
    return world
