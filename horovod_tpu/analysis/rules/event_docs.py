"""HVD011: event kinds drifting from the EVENT_CATALOG contract.

`horovod_tpu.obs.events.EVENT_CATALOG` declares every event ``kind``
the subsystems may emit, with the one-line description an operator
reads in docs/observability.md's event table (regenerated from the
catalog by ``python -m horovod_tpu.analysis --write-event-table``).
Two drift directions break that contract:

* an ``events.emit("kind", ...)`` call (through any alias of the
  events module, including function-local imports) with a literal
  kind not in the catalog emits an event no doc or dashboard knows
  to grep for (flagged at the emit site);
* a catalog entry whose kind is never emitted anywhere is a dead
  promise — the runbook tells operators to watch for an event that
  cannot occur (flagged at the catalog line).

Dynamic kinds (a variable first argument) are out of scope for the
literal scan; keep kinds literal at emit sites — that is what makes
them greppable in the first place.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from horovod_tpu.analysis.core import Finding, RuleMeta, const_str

RULE = RuleMeta(
    id="HVD011",
    name="event-catalog-drift",
    severity="error",
    doc="events.emit() with a literal kind not declared in "
        "obs/events.py EVENT_CATALOG (undocumented event), or a "
        "catalog entry whose kind is never emitted (dead promise).")

_EVENTS_MODULE = "obs/events.py"
_EVENTS_DOTTED = "horovod_tpu.obs.events"


def _events_module(project):
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_EVENTS_MODULE):
            return mi
    return None


def _catalog_from_tree(tree) -> Dict[str, int]:
    """{kind: lineno} from the ``EVENT_CATALOG = {...}`` literal."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            tgts = [t.id for t in node.targets
                    if isinstance(t, ast.Name)]
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)):
            tgts = [node.target.id]
        else:
            continue
        if "EVENT_CATALOG" not in tgts:
            continue
        if isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                k = const_str(key) if key is not None else None
                if k:
                    out[k] = key.lineno
    return out


def _live_catalog() -> Dict[str, int]:
    try:
        from horovod_tpu.obs import events as _ev
        return {k: 0 for k in getattr(_ev, "EVENT_CATALOG", {})}
    except ImportError:    # analyzing a foreign tree — static only
        return {}


def _emit_aliases(mi) -> Tuple[Set[str], Set[str]]:
    """(module aliases of obs.events, direct names bound to its
    ``emit``) — scanned over the WHOLE tree, because subsystems import
    the events module function-locally (`from horovod_tpu.obs import
    events as _events` inside the method that emits)."""
    mods: Set[str] = set()
    fns: Set[str] = set()
    for node in ast.walk(mi.src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _EVENTS_DOTTED and alias.asname:
                    mods.add(alias.asname)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                if (mod.endswith("obs") and alias.name == "events"):
                    mods.add(local)
                elif (mod.endswith("obs.events")
                      and alias.name == "emit"):
                    fns.add(local)
    return mods, fns


def emit_sites(project) -> List[Tuple[str, int, int, str]]:
    """[(path, line, col, kind)] — every literal-kind emit through an
    events-module alias, outside obs/events.py itself."""
    out = []
    for mi in project.symbols.modules.values():
        if mi.path.endswith(_EVENTS_MODULE):
            continue
        mods, fns = _emit_aliases(mi)
        if not mods and not fns:
            continue
        for node in ast.walk(mi.src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            hit = ((isinstance(fn, ast.Attribute)
                    and fn.attr == "emit"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in mods)
                   or (isinstance(fn, ast.Name) and fn.id in fns))
            if not hit:
                continue
            kind = const_str(node.args[0])
            if kind:
                out.append((mi.path, node.lineno, node.col_offset,
                            kind))
    return out


def check(project):
    ev_mi = _events_module(project)
    if ev_mi is not None:
        catalog = _catalog_from_tree(ev_mi.src.tree)
    else:
        catalog = _live_catalog()

    sites = emit_sites(project)
    for path, line, col, kind in sites:
        if kind in catalog:
            continue
        yield Finding(
            RULE.id, RULE.severity, path, line, col,
            f"event kind {kind!r} emitted but not declared in "
            f"EVENT_CATALOG (horovod_tpu/obs/events.py) — "
            f"undocumented events never reach the "
            f"docs/observability.md table operators grep from")

    # Dead-promise direction only when the events module itself is in
    # the analyzed set — a subtree run without the emitters would call
    # every entry dead.
    if ev_mi is None:
        return
    emitted = {kind for (_, _, _, kind) in sites}
    for kind in sorted(catalog):
        if kind not in emitted:
            yield Finding(
                RULE.id, RULE.severity, ev_mi.path, catalog[kind], 0,
                f"EVENT_CATALOG entry {kind!r} is never emitted by "
                f"any subsystem — dead promise in the operator docs; "
                f"emit it or delete the entry")
