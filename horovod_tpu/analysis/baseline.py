"""hvdlint baseline: the committed debt ledger.

A baseline file records findings that existed when a rule landed, so
the CI gate can fail on NEW findings only while the old ones are paid
down. Matching is by ``(rule, path, message)`` OCCURRENCE COUNTS —
line numbers drift with unrelated edits and must not un-baseline a
finding, but a SECOND violation with an identical message (rule
messages don't always carry the enclosing function) must still fail
the gate, so each baselined key absorbs only as many findings as were
recorded.

This repo ships an EMPTY baseline (`.hvdlint-baseline.json`): every
true positive in the tree was fixed or suppressed with a reasoned
comment when the analyzer landed, and the gate keeps it that way. The
workflow for adopting hvdlint elsewhere::

    python -m horovod_tpu.analysis --write-baseline  # snapshot debt
    python -m horovod_tpu.analysis                   # exits 0
    <introduce a regression>                         # exits 1
"""

from __future__ import annotations

import collections
import json
import os
from typing import Counter, List, Tuple

from horovod_tpu.analysis.core import Finding

VERSION = 1

Key = Tuple[str, str, str]


def load(path: str) -> Counter[Key]:
    """Baselined finding keys with occurrence counts; a missing file
    is an empty baseline (a malformed one raises — CI must not
    silently pass)."""
    if not os.path.exists(path):
        return collections.Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != VERSION:
        raise ValueError(
            f"{path}: unsupported hvdlint baseline version "
            f"{data.get('version')!r} (expected {VERSION})")
    return collections.Counter(
        (f["rule"], f["path"], f["message"])
        for f in data["findings"])


def save(path: str, findings: List[Finding]):
    data = {
        "version": VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message}
            for f in findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def split(findings: List[Finding], baselined):
    """(new, old) — each baselined key absorbs at most its recorded
    occurrence count (in file order); the overflow is new. Accepts any
    iterable/mapping of keys (a set counts each key once)."""
    remaining = collections.Counter(baselined)
    new, old = [], []
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
