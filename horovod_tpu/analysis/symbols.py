"""hvdlint cross-file symbol table.

Indexes every module-level function, class, and method across the
analyzed file set, resolves decorators (``@hot_path`` markers,
``jax.jit`` / ``functools.partial(jax.jit, ...)`` / ``vmap`` /
``shard_map`` wrappers plus their static argument sets), records each
module's import aliases, and builds a conservative call graph:

* ``name(...)``            -> same-module function, else a
  ``from m import name`` target resolved into the analyzed set;
* ``alias.attr(...)``      -> module-alias resolution (``import m as
  alias`` / ``from pkg import m``);
* ``self.attr(...)``       -> the enclosing class's method;
* ``anything.attr(...)``   -> the UNION of every analyzed class's
  method named ``attr`` (receiver types are not inferred — for
  reachability analysis over-approximation is the safe direction).

`hot_reachable()` runs BFS from every ``@hot_path``-annotated function
— the HVD001 universe.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from horovod_tpu.analysis.core import SourceFile, dotted_name

JIT_NAMES = {"jax.jit", "jit"}
_VMAP_NAMES = {"jax.vmap", "vmap"}
_SHARD_MAP_NAMES = {"jax.shard_map", "shard_map",
                    "jax.experimental.shard_map.shard_map"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


class FunctionInfo:
    """One function or method definition."""

    def __init__(self, module: str, name: str, cls: Optional[str],
                 node: ast.FunctionDef, src: SourceFile):
        self.module = module          # relpath of the defining file
        self.name = name
        self.cls = cls                # class name or None
        self.node = node
        self.src = src
        self.qname = (f"{module}:{cls}.{name}" if cls
                      else f"{module}:{name}")
        self.hot_entry = False
        self.jit_kind: Optional[str] = None   # "jit"|"vmap"|"shard_map"
        self.static_params: Set[str] = set()
        self._analyze_decorators()

    def _analyze_decorators(self):
        for dec in self.node.decorator_list:
            target, kwargs = _unwrap_decorator(dec)
            if target is None:
                continue
            if target.split(".")[-1] == "hot_path":
                self.hot_entry = True
            elif target in JIT_NAMES:
                self.jit_kind = "jit"
                self.static_params |= _static_params(self.node, kwargs)
            elif target in _VMAP_NAMES:
                self.jit_kind = self.jit_kind or "vmap"
            elif target in _SHARD_MAP_NAMES:
                self.jit_kind = self.jit_kind or "shard_map"

    def param_names(self) -> List[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] +
                [p.arg for p in a.args] +
                [p.arg for p in a.kwonlyargs])


def _unwrap_decorator(dec: ast.AST) -> Tuple[Optional[str], dict]:
    """(dotted target, keyword dict) for a decorator expression.
    ``@functools.partial(jax.jit, static_argnames=..)`` unwraps to
    ``jax.jit`` with partial's keywords; ``@jax.jit(donate..=..)``
    keeps its own keywords."""
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        kwargs = {kw.arg: kw.value for kw in dec.keywords if kw.arg}
        if fn in _PARTIAL_NAMES and dec.args:
            inner = dotted_name(dec.args[0])
            return inner, kwargs
        return fn, kwargs
    return dotted_name(dec), {}


def _static_params(node: ast.FunctionDef, kwargs: dict) -> Set[str]:
    """Parameter names named static by static_argnames/static_argnums
    keywords (literal strings / ints / tuples thereof only)."""
    out: Set[str] = set()
    params = ([p.arg for p in node.args.posonlyargs] +
              [p.arg for p in node.args.args])
    names = kwargs.get("static_argnames")
    if names is not None:
        for el in (names.elts if isinstance(names, (ast.Tuple, ast.List))
                   else [names]):
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
    nums = kwargs.get("static_argnums")
    if nums is not None:
        for el in (nums.elts if isinstance(nums, (ast.Tuple, ast.List))
                   else [nums]):
            if (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)
                    and 0 <= el.value < len(params)):
                out.add(params[el.value])
    return out


class ClassInfo:
    def __init__(self, module: str, name: str, node: ast.ClassDef,
                 src: SourceFile):
        self.module = module
        self.name = name
        self.node = node
        self.src = src
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases = [dotted_name(b) for b in node.bases]


class ModuleInfo:
    def __init__(self, src: SourceFile):
        self.src = src
        self.path = src.path
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # local alias -> imported module relpath-ish dotted name
        self.module_aliases: Dict[str, str] = {}
        # local name -> (module dotted name, original name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        # module-level names bound to jit wrappers: f = jax.jit(g)
        self.jit_aliases: Dict[str, Optional[str]] = {}
        # alias -> the jax.jit(...) Call node (for its static_arg* kws)
        self._jit_alias_calls: Dict[str, ast.Call] = {}
        self._index()

    def _index(self):
        for node in self.src.tree.body:
            if isinstance(node, ast.FunctionDef):
                self.functions[node.name] = FunctionInfo(
                    self.path, node.name, None, node, self.src)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(self.path, node.name, node, self.src)
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        ci.methods[sub.name] = FunctionInfo(
                            self.path, sub.name, node.name, sub,
                            self.src)
                self.classes[node.name] = ci
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(node)
            elif isinstance(node, ast.Assign):
                self._index_assign(node)

    def _index_import(self, node):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                self.module_aliases[local] = (alias.name if alias.asname
                                              else alias.name.split(".")[0])
        else:
            mod = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                # `from pkg import mod` can bind a module; record both
                # interpretations — resolution tries module-alias
                # first, then function import.
                self.module_aliases.setdefault(
                    local, f"{mod}.{alias.name}" if mod else alias.name)
                self.from_imports[local] = (mod, alias.name)

    def _index_assign(self, node: ast.Assign):
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in JIT_NAMES):
            inner = (dotted_name(node.value.args[0])
                     if node.value.args else None)
            self.jit_aliases[node.targets[0].id] = inner
            if inner is not None:
                self._jit_alias_calls[node.targets[0].id] = node.value


class SymbolTable:
    def __init__(self, files: List[SourceFile]):
        self.modules: Dict[str, ModuleInfo] = {
            f.path: ModuleInfo(f) for f in files}
        # dotted module name (horovod_tpu.serving.slots) -> relpath
        self.dotted_to_path: Dict[str, str] = {}
        for path in self.modules:
            dotted = path[:-3].replace("/", ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[:-len(".__init__")]
            self.dotted_to_path[dotted] = path
        self.methods_by_name: Dict[str, List[FunctionInfo]] = {}
        for mi in self.modules.values():
            for ci in mi.classes.values():
                for m in ci.methods.values():
                    self.methods_by_name.setdefault(m.name, []).append(m)
        # A module-level `step = jax.jit(_step, ...)` compiles _step
        # exactly as the decorator form would: mark the wrapped def so
        # HVD002 traces its params (HVD001/HVD003 already resolve
        # these call sites via is_jit_callee).
        for mi in self.modules.values():
            for alias, call in mi._jit_alias_calls.items():
                f = mi.functions.get(mi.jit_aliases[alias] or "")
                if f is not None and f.jit_kind is None:
                    f.jit_kind = "jit"
                    kwargs = {kw.arg: kw.value
                              for kw in call.keywords if kw.arg}
                    f.static_params |= _static_params(f.node, kwargs)

    # -- lookups ------------------------------------------------------

    def all_functions(self):
        for mi in self.modules.values():
            yield from mi.functions.values()
            for ci in mi.classes.values():
                yield from ci.methods.values()

    def module_by_dotted(self, dotted: str) -> Optional[ModuleInfo]:
        path = self.dotted_to_path.get(dotted)
        # Tolerate absolute dotted names whose prefix isn't in the
        # analyzed set (e.g. analyzing a subtree).
        if path is None:
            for cand, p in self.dotted_to_path.items():
                if cand.endswith("." + dotted) or cand == dotted:
                    path = p
                    break
        return self.modules.get(path) if path else None

    def is_jit_callee(self, fi_or_none, mi: ModuleInfo,
                      call: ast.Call) -> bool:
        """Is this call site invoking a known jit-compiled function —
        a resolved @jit def, or a module-level ``f = jax.jit(g)``
        alias?"""
        if fi_or_none is not None and fi_or_none.jit_kind == "jit":
            return True
        name = dotted_name(call.func)
        return bool(name and name in mi.jit_aliases)

    def resolve_call(self, mi: ModuleInfo, cls: Optional[ClassInfo],
                     call: ast.Call) -> List[FunctionInfo]:
        fn = call.func
        out: List[FunctionInfo] = []
        if isinstance(fn, ast.Name):
            f = mi.functions.get(fn.id)
            if f is not None:
                return [f]
            if fn.id in mi.from_imports:
                mod_dotted, orig = mi.from_imports[fn.id]
                target = self.module_by_dotted(mod_dotted)
                if target is not None:
                    f = target.functions.get(orig)
                    if f is not None:
                        return [f]
                    c = target.classes.get(orig)
                    if c is not None and "__init__" in c.methods:
                        return [c.methods["__init__"]]
            c = mi.classes.get(fn.id)
            if c is not None and "__init__" in c.methods:
                return [c.methods["__init__"]]
            return out
        if isinstance(fn, ast.Attribute):
            base = fn.value
            # self.method(...)
            if (isinstance(base, ast.Name) and base.id == "self"
                    and cls is not None):
                m = cls.methods.get(fn.attr)
                if m is not None:
                    return [m]
            # module_alias.func(...)
            if isinstance(base, ast.Name):
                dotted = mi.module_aliases.get(base.id)
                if dotted is not None:
                    target = self.module_by_dotted(dotted)
                    if target is not None:
                        f = target.functions.get(fn.attr)
                        if f is not None:
                            return [f]
                        c = target.classes.get(fn.attr)
                        if c is not None and "__init__" in c.methods:
                            return [c.methods["__init__"]]
            # anything.method(...): union over analyzed classes.
            return list(self.methods_by_name.get(fn.attr, ()))
        return out

    # -- hot-path reachability ---------------------------------------

    def hot_entries(self) -> List[FunctionInfo]:
        return [f for f in self.all_functions() if f.hot_entry]

    def hot_reachable(self) -> Dict[str, Tuple[FunctionInfo, str]]:
        """{qname: (function, entry qname it is reachable from)} via
        BFS over the call graph from every @hot_path entry. The entry
        recorded is the lexicographically first one that reaches the
        function (deterministic messages)."""
        reach: Dict[str, Tuple[FunctionInfo, str]] = {}
        for entry in sorted(self.hot_entries(),
                            key=lambda f: f.qname):
            todo = [entry]
            while todo:
                fi = todo.pop()
                if fi.qname in reach:
                    continue
                reach[fi.qname] = (fi, entry.qname)
                mi = self.modules[fi.module]
                ci = mi.classes.get(fi.cls) if fi.cls else None
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        todo.extend(self.resolve_call(mi, ci, node))
        return reach
