"""horovod_tpu — a TPU-native distributed training framework.

Capability-parity rebuild of Horovod v0.10 (reference: chenkaiidy/horovod),
re-designed for TPU hardware: the MPI/NCCL data plane becomes XLA collectives
(`psum` / `all_gather` / `ppermute`) over a `jax.sharding.Mesh`; the rank-0
coordinator negotiation (reference `horovod/tensorflow/mpi_ops.cc:1195-1509`)
is replaced by SPMD compile-time collective ordering, with a compact native
C++ control plane for bootstrap, cross-rank metadata validation, timeline
tracing and stall detection.

Top-level API (parity with `horovod/tensorflow/__init__.py` and
`horovod/tensorflow/mpi_ops.py` in the reference):

    import horovod_tpu as hvd
    hvd.init()
    hvd.rank(), hvd.size(), hvd.local_rank()
    hvd.allreduce(x), hvd.allgather(x), hvd.broadcast(x, root_rank)
    hvd.DistributedOptimizer(optax_tx)
    hvd.broadcast_global_variables(params, root_rank)
"""

import horovod_tpu._jax_graft  # noqa: F401  (backfills jax.shard_map
#                                on old jax BEFORE any module traces one)
from horovod_tpu.runtime.bootstrap import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    process_rank,
    num_processes,
    mesh,
)
from horovod_tpu.ops.eager import (
    allreduce,
    allgather,
    broadcast,
    alltoall,
    reducescatter,
    per_rank,
    PerRank,
)
from horovod_tpu.ops import collectives as spmd
from horovod_tpu.jax import (
    DistributedOptimizer,
    DistributedGradientTape,
    allreduce_gradients,
    broadcast_global_variables,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_object,
    allgather_object,
    grouped_allreduce,
    make_train_step,
    make_global_batch,
)
from horovod_tpu.ops.sparse import IndexedSlices
from horovod_tpu.runtime.config import config
from horovod_tpu.utils.timeline import start_timeline, stop_timeline
from horovod_tpu import resilience  # chaos / retry / elastic (docs/resilience.md)

__version__ = "0.10.0"  # mirrors the reference's version (setup.py:348)

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size",
    "process_rank", "num_processes", "mesh",
    "allreduce", "allgather", "broadcast", "alltoall", "reducescatter",
    "per_rank", "PerRank", "spmd",
    "DistributedOptimizer", "DistributedGradientTape", "allreduce_gradients",
    "broadcast_global_variables", "broadcast_parameters",
    "broadcast_optimizer_state", "broadcast_object",
    "allgather_object", "grouped_allreduce",
    "make_train_step", "make_global_batch", "IndexedSlices", "config",
    "start_timeline", "stop_timeline", "resilience",
]
