"""Bounded exponential-backoff retry with jitter and a deadline.

One `RetryPolicy` is shared by checkpoint I/O (`utils/checkpoint.py`)
and data loading (`horovod_tpu.data`): transient filesystem faults —
GCS 5xx on a TPU pod, an injected `ChaosError` in tests — are retried
with exponential backoff; programming errors are not (the default
filter retries `OSError` and `ChaosError` only). The policy is a
frozen value object so one instance can be shared across threads.
"""

from __future__ import annotations

import random
import sys
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple, Type

from horovod_tpu.resilience.chaos import ChaosError


class RetryError(RuntimeError):
    """All attempts failed (or the deadline passed). ``__cause__``
    carries the last underlying exception; `attempts` how many ran."""

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


@dataclass(frozen=True)
class RetryPolicy:
    """max_attempts total tries; sleep base * multiplier**k, capped at
    max_delay, +/- jitter fraction; give up early once deadline_s of
    wall clock has passed. `retry_on` filters which exceptions are
    transient — anything else propagates immediately."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (OSError, ChaosError)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.multiplier < 1.0:
            raise ValueError(
                f"multiplier must be >= 1, got {self.multiplier}")

    def delays(self) -> Iterator[float]:
        """The backoff schedule (one entry per retry, i.e.
        max_attempts - 1 entries)."""
        d = self.base_delay_s
        for _ in range(self.max_attempts - 1):
            j = 1.0 + self.jitter * (2 * random.random() - 1)
            yield min(d, self.max_delay_s) * j
            d *= self.multiplier

    def call(self, fn: Callable, *args,
             on_retry: Optional[Callable] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy.

        ``on_retry(exc, attempt, delay_s)`` fires before each backoff
        sleep (attempt is 1-based); the default logs to stderr — the
        CI chaos smoke greps for that line.
        """
        t0 = time.time()
        last: Optional[BaseException] = None
        delays = self.delays()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:  # noqa: PERF203
                last = e
                if attempt >= self.max_attempts:
                    break
                delay = next(delays)
                if (self.deadline_s is not None
                        and time.time() - t0 + delay > self.deadline_s):
                    break
                if on_retry is not None:
                    on_retry(e, attempt, delay)
                else:
                    sys.stderr.write(
                        f"horovod_tpu: transient failure ({e!r}); "
                        f"retry {attempt}/{self.max_attempts - 1} in "
                        f"{delay:.2f}s\n")
                time.sleep(delay)
        raise RetryError(
            f"gave up after {attempt} attempt(s): {last!r}",
            attempts=attempt) from last


def default_io_policy() -> RetryPolicy:
    """The shared checkpoint/data-loading policy. ``HVD_IO_RETRIES``
    overrides the attempt count (0 disables retries entirely)."""
    from horovod_tpu.runtime.config import env_int
    return RetryPolicy(max_attempts=max(1, env_int("HVD_IO_RETRIES", 3)))
