"""Preemption-safe training: emergency checkpoints, NaN rollback.

TPU pods are preempted on schedule (maintenance events, spot
reclaims): the runtime gets SIGTERM and a grace window. MLPerf-scale
TPU runs (arXiv:1909.09756) treat checkpoint-resume as first-class —
a preempted run must cost at most `save_every` steps of progress, not
the job. Three pieces deliver that here:

* `PreemptionHandler` — installs SIGTERM/SIGINT handlers that only
  set a flag; the *training loop* (which owns the device and the
  up-to-date state) checks `triggered` between steps and writes the
  emergency checkpoint from a sane context, never from inside a
  signal frame mid-XLA-dispatch.
* `NaNGuard` — watches the loss stream for NaN/inf or a spike; the
  `ElasticTrainer` answers a trip by rolling back to the last good
  checkpoint instead of dying (the divergence-containment recipe).
* `ElasticTrainer` — resume discovery (latest-GOOD: partial/corrupt
  newest checkpoints are skipped, `utils/checkpoint.py`), periodic
  saves with `keep` retention, emergency save on preemption, and
  rollback — the loop-side glue `examples/jax_checkpoint_resume.py`
  demonstrates.
"""

from __future__ import annotations

import math
import signal
import sys
import threading
import time
from typing import Any, Callable, Optional

from horovod_tpu.resilience.retry import RetryPolicy


class PreemptionHandler:
    """Flag-setting SIGTERM/SIGINT handler (context manager).

    The handler itself does no I/O: Python signal handlers run between
    bytecodes on the main thread, possibly inside an XLA dispatch or a
    lock — checkpointing there can deadlock. It records the signal and
    the time; the training loop polls `triggered` at step boundaries
    (milliseconds apart) and saves from clean context. A second
    delivery of the same signal falls through to the previous handler
    — a stuck loop can still be killed with a second Ctrl-C.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), *,
                 callback: Optional[Callable[[int], None]] = None):
        self._signals = tuple(signals)
        self._callback = callback
        self._event = threading.Event()
        self._prev: dict = {}
        self.signum: Optional[int] = None
        self.t_signal: Optional[float] = None

    def install(self) -> "PreemptionHandler":
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _on_signal(self, signum, frame):
        # No observability emission HERE: a Python signal handler runs
        # between bytecodes on the main thread, and the event log's
        # lock could already be held by the interrupted frame — the
        # loop-side consumers (`ElasticTrainer.after_step`) emit the
        # preemption event from clean context instead.
        if self._event.is_set():
            # Second signal: restore the previous disposition and
            # re-deliver so a wedged loop still dies (SIG_DFL SIGTERM
            # terminates via the re-raise below) or KeyboardInterrupts
            # (SIGINT).
            prev = self._prev.get(signum, signal.SIG_DFL)
            if prev is None:
                # signal.signal returns None for handlers installed by
                # non-Python code (C extensions); we cannot restore
                # those — fall back to the default disposition.
                prev = signal.SIG_DFL
            signal.signal(signum, prev)
            if callable(prev):
                prev(signum, frame)
            elif signum == signal.SIGINT:
                raise KeyboardInterrupt
            else:
                import os
                os.kill(os.getpid(), signum)  # restored disposition
            return
        self.signum = signum
        self.t_signal = time.time()
        self._event.set()
        if self._callback is not None:
            self._callback(signum)

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        self.uninstall()


class NaNGuard:
    """Detects a diverged step from its loss: NaN/inf always trips;
    a finite loss trips once it exceeds ``spike_factor`` x the median
    of the last ``window`` good losses (spikes only count once the
    window has ``min_history`` entries — early training is noisy)."""

    def __init__(self, *, spike_factor: float = 100.0,
                 window: int = 32, min_history: int = 8):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}")
        self.spike_factor = spike_factor
        self.window = window
        self.min_history = min_history
        self._good: list = []
        self.trips = 0

    def check(self, loss: float) -> bool:
        """True ⇒ this step is bad (do not keep its state)."""
        loss = float(loss)
        if not math.isfinite(loss):
            self.trips += 1
            return True
        if len(self._good) >= self.min_history:
            xs = sorted(self._good)
            median = xs[len(xs) // 2]
            if median > 0 and loss > self.spike_factor * median:
                self.trips += 1
                return True
        self._good.append(loss)
        if len(self._good) > self.window:
            self._good.pop(0)
        return False


class ElasticTrainer:
    """Checkpoint-directory-centric resilience for a training loop::

        trainer = ElasticTrainer(ckpt_dir, save_every=50, keep=3)
        state, start = trainer.resume(like=state)   # latest GOOD step
        for i in range(start, steps):
            state, loss = step(state, batch())
            state = trainer.after_step(i + 1, state, loss)
            if trainer.should_stop:       # SIGTERM/SIGINT landed —
                break                     # emergency ckpt already cut

    `after_step` is the one hook: it rolls back to the last good
    checkpoint when the `NaNGuard` trips (returning the restored
    state), saves every `save_every` steps, and cuts an emergency
    synchronous save the moment the preemption handler has triggered.
    Saves go through `utils.checkpoint.save_step` — rank-0-only,
    atomic (temp + rename), retried under the shared `RetryPolicy`.
    """

    def __init__(self, directory: str, *, save_every: int = 50,
                 keep: int = 3, block: bool = False,
                 guard: Optional[NaNGuard] = None,
                 handler: Optional[PreemptionHandler] = None,
                 retry: Optional[RetryPolicy] = None,
                 install_signals: bool = True):
        self.directory = directory
        self.save_every = save_every
        self.keep = keep
        self.block = block
        self.guard = guard if guard is not None else NaNGuard()
        self.retry = retry
        self.handler = handler
        if self.handler is None and install_signals:
            self.handler = PreemptionHandler().install()
        self._owns_handler = self.handler is not None and handler is None
        self._like: Any = None
        self._last_good_step: Optional[int] = None
        self._emergency_done = False
        self.rollbacks = 0

    def close(self):
        """Uninstall the signal handlers this trainer installed (a
        no-op for a caller-provided or disabled handler). Without
        this, Ctrl-C after the training loop would only set a stale
        flag instead of interrupting. Idempotent; `with` calls it.

        Note: installing handlers requires the main thread — construct
        with ``install_signals=False`` off the main thread and poll a
        caller-owned handler instead."""
        if self._owns_handler and self.handler is not None:
            self.handler.uninstall()
            self._owns_handler = False

    def __enter__(self) -> "ElasticTrainer":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()

    # -- resume -------------------------------------------------------

    def resume(self, *, like: Any = None, broadcast: bool = False):
        """(state, start_step) from the latest GOOD checkpoint —
        corrupt/partial newest steps are skipped with a warning. On a
        fresh directory returns ``(like, 0)``: the template passes
        through unchanged, so the documented
        ``state, start = trainer.resume(like=state)`` loop works on
        the very first run too. Keeps `like` as the rollback
        template."""
        from horovod_tpu.utils import checkpoint as ckpt
        self._like = like
        out = ckpt.restore_latest(self.directory, like=like,
                                  broadcast=broadcast, with_step=True)
        if out is None:
            return like, 0
        restored, step = out
        self._last_good_step = step
        return restored, int(step)

    # -- the per-step hook --------------------------------------------

    def after_step(self, step: int, state: Any, loss) -> Any:
        """Fold one finished step into the resilience machinery; see
        class docstring. Returns the state the loop should continue
        from (the rolled-back one after a NaN/spike trip)."""
        if self.guard.check(loss):
            # No emergency save needed even if a preemption signal
            # landed this same step: the rolled-back state IS the last
            # good checkpoint, already durable on disk — the diverged
            # steps since it are precisely what must not be saved.
            return self._rollback(step, float(loss))
        if (self.handler is not None and self.handler.triggered
                and not self._emergency_done):
            self._emergency_save(step, state)
            return state
        # Deliberately NOT gated on the preemption flag: a loop that
        # chooses to keep training after the signal must keep its
        # periodic checkpoints.
        if self.save_every > 0 and step % self.save_every == 0:
            from horovod_tpu.utils import checkpoint as ckpt
            ckpt.save_step(self.directory, step, state,
                           keep=self.keep, block=self.block,
                           retry=self.retry)
            self._last_good_step = step
        return state

    @property
    def should_stop(self) -> bool:
        return self.handler is not None and self.handler.triggered

    def _rollback(self, step: int, loss: float) -> Any:
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.utils import checkpoint as ckpt
        self.rollbacks += 1
        out = ckpt.restore_latest(self.directory, like=self._like,
                                  with_step=True)
        if out is None:
            raise FloatingPointError(
                f"step {step}: non-finite/spiking loss ({loss}) with "
                f"no checkpoint to roll back to in {self.directory}")
        restored, good_step = out
        # The restore may have fallen back PAST what we last wrote
        # (that checkpoint could itself be the corrupt one).
        self._last_good_step = good_step
        _obs_catalog.resilience_metrics()["rollbacks"].inc()
        _events.emit("training.rollback", step=step, loss=loss,
                     restored_step=int(good_step))
        sys.stderr.write(
            f"horovod_tpu: step {step} diverged (loss={loss}); rolled "
            f"back to checkpoint step {good_step} "
            f"(rollback #{self.rollbacks})\n")
        return restored

    def _emergency_save(self, step: int, state: Any):
        """Synchronous (the process is about to die — an async write
        would race teardown), once."""
        if self._emergency_done:
            return
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.utils import checkpoint as ckpt
        ckpt.wait_pending()
        ckpt.save_step(self.directory, step, state, keep=self.keep,
                       block=True, retry=self.retry)
        self._last_good_step = step
        self._emergency_done = True
        _obs_catalog.resilience_metrics()["emergency_saves"].inc()
        _events.emit(
            "training.emergency_save", step=step,
            signum=getattr(self.handler, "signum", None))
        sys.stderr.write(
            f"horovod_tpu: preemption signal "
            f"{getattr(self.handler, 'signum', None)} — emergency "
            f"checkpoint at step {step} in {self.directory}\n")
