"""Preemption-safe training: emergency checkpoints, NaN rollback.

TPU pods are preempted on schedule (maintenance events, spot
reclaims): the runtime gets SIGTERM and a grace window. MLPerf-scale
TPU runs (arXiv:1909.09756) treat checkpoint-resume as first-class —
a preempted run must cost at most `save_every` steps of progress, not
the job. Three pieces deliver that here:

* `PreemptionHandler` — installs SIGTERM/SIGINT handlers that only
  set a flag; the *training loop* (which owns the device and the
  up-to-date state) checks `triggered` between steps and writes the
  emergency checkpoint from a sane context, never from inside a
  signal frame mid-XLA-dispatch.
* `NaNGuard` — watches the loss stream for NaN/inf or a spike; the
  `ElasticTrainer` answers a trip by rolling back to the last good
  checkpoint instead of dying (the divergence-containment recipe).
* `ElasticTrainer` — resume discovery (latest-GOOD: partial/corrupt
  newest checkpoints are skipped, `utils/checkpoint.py`), periodic
  saves with `keep` retention, emergency save on preemption, and
  rollback — the loop-side glue `examples/jax_checkpoint_resume.py`
  demonstrates.

Exact resume (docs/resilience.md "Exact resume"): a checkpoint that
captures model/optimizer state alone makes a resumed run *silently
lossy* — the interrupted epoch's remaining batches are replayed or
skipped depending on where the loop restarts. `TrainSnapshot` makes
the FULL training state one checkpointable unit: the pytree plus the
data-pipeline cursor (`ShardedDataset.state()`), the host RNG, and
the NaN-guard history, saved atomically by every `save_step` /
emergency save (the cursor rides the `aux` sidecar) and restored by
`resume()`. A missing/corrupt/incompatible cursor degrades to the
epoch boundary — loudly: `hvd_resilience_cursor_fallbacks_total`
increments, a `training.cursor_fallback` event fires, and
`resume_gap_batches` reports how many batches the fallback replays.
`resilience/equivalence.py` proves the exactly-once contract
end-to-end under chaos-injected kills.
"""

from __future__ import annotations

import dataclasses
import math
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from horovod_tpu.resilience.retry import RetryPolicy

# Version stamp of the TrainSnapshot aux schema; restore refuses a
# different version (the cursor would be meaningless) and falls back
# to the epoch boundary.
SNAPSHOT_SCHEMA = 1


#: Default signal set: SIGTERM/SIGINT are the kill path; SIGUSR1 is
#: the cloud *preemption notice* (GCE shutdown scripts, k8s preStop
#: hooks and TPU maintenance notifiers can deliver it ahead of the
#: real SIGTERM) — catching it starts the emergency save BEFORE the
#: hard signal lands, with the whole HVD_PREEMPT_GRACE_S window still
#: in hand.
DEFAULT_PREEMPT_SIGNALS = (signal.SIGTERM, signal.SIGINT,
                           signal.SIGUSR1)


class PreemptionHandler:
    """Flag-setting SIGTERM/SIGINT/SIGUSR1 handler (context manager).

    The handler itself does no I/O: Python signal handlers run between
    bytecodes on the main thread, possibly inside an XLA dispatch or a
    lock — checkpointing there can deadlock. It records the signal and
    the time; the training loop polls `triggered` at step boundaries
    (milliseconds apart) and saves from clean context.

    Escalation model: SIGUSR1 is an advance *notice* — it only ever
    sets the flag (clouds may deliver several; none should kill a
    loop that is busy saving). The FIRST hard signal (SIGTERM/SIGINT)
    after a notice is absorbed too — it is the expected second act of
    a preemption, arriving while the emergency checkpoint may still
    be in flight. Any further hard signal — and, without a notice,
    the SECOND hard signal of any kind — falls through to the
    previous disposition, so a wedged loop can still be killed with a
    second Ctrl-C (or SIGTERM then Ctrl-C).

    Grace window: ``HVD_PREEMPT_GRACE_S`` (default 30 s) is how long
    the platform promises the host survives past the first notice.
    `grace_remaining()` is the loop's save budget — e.g. skip an
    optional validation pass when it dips low.
    """

    def __init__(self, signals=DEFAULT_PREEMPT_SIGNALS, *,
                 callback: Optional[Callable[[int], None]] = None,
                 grace_s: Optional[float] = None):
        if grace_s is None:
            from horovod_tpu.runtime.config import env_float
            grace_s = env_float("HVD_PREEMPT_GRACE_S", 30.0)
        self._signals = tuple(signals)
        self._callback = callback
        self._event = threading.Event()
        self._prev: dict = {}
        self._hard_seen: set = set()
        self._notice_seen = False
        self.grace_s = float(grace_s)
        self.signum: Optional[int] = None
        self.t_signal: Optional[float] = None

    def install(self) -> "PreemptionHandler":
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _on_signal(self, signum, frame):
        # No observability emission HERE: a Python signal handler runs
        # between bytecodes on the main thread, and the event log's
        # lock could already be held by the interrupted frame — the
        # loop-side consumers (`ElasticTrainer.after_step`) emit the
        # preemption event from clean context instead.
        hard = signum != signal.SIGUSR1
        # A SIGUSR1 notice buys exactly ONE hard-signal absorption:
        # the SIGTERM that follows a cloud preemption notice is the
        # preemption's expected second act (the emergency save may
        # still be writing). Without a notice, a second hard signal
        # of ANY kind escalates — the pre-existing wedged-loop escape
        # hatch (SIGTERM then Ctrl-C must still kill).
        absorb_hard = (self._notice_seen and not self._hard_seen)
        if self._event.is_set() and hard and not absorb_hard:
            # Escalating HARD signal: restore the previous disposition
            # and re-deliver so a wedged loop still dies (SIG_DFL
            # SIGTERM terminates via the re-raise below) or
            # KeyboardInterrupts (SIGINT). A SIGUSR1 notice — however
            # many times the cloud repeats it — never escalates.
            prev = self._prev.get(signum, signal.SIG_DFL)
            if prev is None:
                # signal.signal returns None for handlers installed by
                # non-Python code (C extensions); we cannot restore
                # those — fall back to the default disposition.
                prev = signal.SIG_DFL
            signal.signal(signum, prev)
            if callable(prev):
                prev(signum, frame)
            elif signum == signal.SIGINT:
                raise KeyboardInterrupt
            else:
                import os
                os.kill(os.getpid(), signum)  # restored disposition
            return
        if self._event.is_set():
            # Notice already active: record the stronger signal (the
            # grace clock keeps running from the FIRST notice — the
            # platform's promise is anchored there).
            self.signum = signum
            if hard:
                self._hard_seen.add(signum)
            else:
                self._notice_seen = True
            return
        self.signum = signum
        self.t_signal = time.time()
        if hard:
            self._hard_seen.add(signum)
        else:
            self._notice_seen = True
        self._event.set()
        if self._callback is not None:
            self._callback(signum)

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def grace_deadline(self) -> Optional[float]:
        """time.time() by which the host may be gone (first notice +
        HVD_PREEMPT_GRACE_S); None before any signal."""
        if self.t_signal is None:
            return None
        return self.t_signal + self.grace_s

    def grace_remaining(self) -> Optional[float]:
        """Seconds of the preemption grace window left (clamped at 0)
        — the emergency-save budget; None before any signal."""
        dl = self.grace_deadline
        if dl is None:
            return None
        return max(0.0, dl - time.time())

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        self.uninstall()


class NaNGuard:
    """Detects a diverged step from its loss: NaN/inf always trips;
    a finite loss trips once it exceeds ``spike_factor`` x the median
    of the last ``window`` good losses (spikes only count once the
    window has ``min_history`` entries — early training is noisy)."""

    def __init__(self, *, spike_factor: float = 100.0,
                 window: int = 32, min_history: int = 8):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}")
        self.spike_factor = spike_factor
        self.window = window
        self.min_history = min_history
        self._good: list = []
        self.trips = 0

    def check(self, loss: float) -> bool:
        """True ⇒ this step is bad (do not keep its state)."""
        loss = float(loss)
        if not math.isfinite(loss):
            self.trips += 1
            return True
        if len(self._good) >= self.min_history:
            xs = sorted(self._good)
            median = xs[len(xs) // 2]
            if median > 0 and loss > self.spike_factor * median:
                self.trips += 1
                return True
        self._good.append(loss)
        if len(self._good) > self.window:
            self._good.pop(0)
        return False

    def state(self) -> Dict:
        """JSON-able snapshot (the TrainSnapshot guard leg): without
        it a resumed run restarts with an empty loss window, and the
        first `min_history` post-resume steps are spike-blind."""
        return {"good": [float(x) for x in self._good],
                "trips": int(self.trips)}

    def restore(self, state: Dict) -> "NaNGuard":
        self._good = [float(x) for x in state.get("good", [])]
        self.trips = int(state.get("trips", 0))
        return self


def _rng_state(rng) -> Dict:
    """JSON-able host-RNG snapshot: `np.random.Generator` (via its
    bit_generator state dict) and legacy `np.random.RandomState`
    (MT19937 key list) both supported — these are the two host-side
    RNGs training loops draw batch/augmentation randomness from."""
    import numpy as np
    if isinstance(rng, np.random.Generator):
        return {"kind": "generator", "state": rng.bit_generator.state}
    if isinstance(rng, np.random.RandomState):
        name, keys, pos, has_gauss, cached = rng.get_state()
        return {"kind": "random_state",
                "state": [name, [int(k) for k in keys], int(pos),
                          int(has_gauss), float(cached)]}
    raise TypeError(
        f"unsupported host RNG {type(rng).__name__}: pass a "
        f"numpy Generator or RandomState")


def _rng_restore(rng, snap: Dict):
    import numpy as np
    kind = snap.get("kind")
    if kind == "generator":
        if not isinstance(rng, np.random.Generator):
            raise TypeError("snapshot holds a Generator state but the "
                            f"trainer's rng is {type(rng).__name__}")
        rng.bit_generator.state = snap["state"]
    elif kind == "random_state":
        if not isinstance(rng, np.random.RandomState):
            raise TypeError("snapshot holds a RandomState state but "
                            f"the trainer's rng is {type(rng).__name__}")
        name, keys, pos, has_gauss, cached = snap["state"]
        rng.set_state((name, np.asarray(keys, np.uint32), int(pos),
                       int(has_gauss), float(cached)))
    else:
        raise ValueError(f"unknown rng snapshot kind {kind!r}")


@dataclasses.dataclass
class TrainSnapshot:
    """The composite unit `resume()` reconstructs: model/optimizer
    pytree + step + the host-side legs of exactly-once training. The
    pytree lands in the Orbax step directory; everything else rides
    the atomic `aux` sidecar (`utils/checkpoint.py::save_step`).

    ``exact`` distinguishes a full restore from the degraded
    epoch-boundary fallback (cursor missing/corrupt/incompatible);
    ``gap_batches`` is how many batches the fallback replays — 0 on
    every exact resume."""

    state: Any
    step: int
    data_state: Optional[Dict] = None
    rng_state: Optional[Dict] = None
    guard_state: Optional[Dict] = None
    exact: bool = True
    gap_batches: int = 0
    schema: int = SNAPSHOT_SCHEMA


class ElasticTrainer:
    """Checkpoint-directory-centric resilience for a training loop::

        trainer = ElasticTrainer(ckpt_dir, save_every=50, keep=3)
        state, start = trainer.resume(like=state)   # latest GOOD step
        for i in range(start, steps):
            state, loss = step(state, batch())
            state = trainer.after_step(i + 1, state, loss)
            if trainer.should_stop:       # SIGTERM/SIGINT landed —
                break                     # emergency ckpt already cut

    `after_step` is the one hook: it rolls back to the last good
    checkpoint when the `NaNGuard` trips (returning the restored
    state), saves every `save_every` steps, and cuts an emergency
    synchronous save the moment the preemption handler has triggered.
    Saves go through `utils.checkpoint.save_step` — rank-0-only,
    atomic (temp + rename), retried under the shared `RetryPolicy`.
    """

    def __init__(self, directory: str, *, save_every: int = 50,
                 keep: int = 3, block: bool = False,
                 guard: Optional[NaNGuard] = None,
                 handler: Optional[PreemptionHandler] = None,
                 retry: Optional[RetryPolicy] = None,
                 install_signals: bool = True,
                 dataset: Any = None, rng: Any = None,
                 migrate_world: bool = False):
        self.directory = directory
        self.save_every = save_every
        self.keep = keep
        self.block = block
        self.guard = guard if guard is not None else NaNGuard()
        self.retry = retry
        self.handler = handler
        if self.handler is None and install_signals:
            self.handler = PreemptionHandler().install()
        self._owns_handler = self.handler is not None and handler is None
        self._like: Any = None
        self._last_good_step: Optional[int] = None
        self._emergency_done = False
        self.rollbacks = 0
        # Exact-resume legs (docs/resilience.md "Exact resume"):
        # attach the ShardedDataset and the host RNG so every save_step
        # snapshots their state in the aux sidecar and resume()
        # restores them. Both optional — a loop without them keeps the
        # PR-2 model-state-only behavior.
        self.dataset = dataset
        self.rng = rng
        if rng is not None:
            _rng_state(rng)  # validate the type NOW, not at save time
        # Elastic resize (docs/resilience.md "Elastic membership"):
        # with migrate_world on, a snapshot cursor from a DIFFERENT
        # (rank, world) is migrated — the dataset rebalances the
        # interrupted epoch's untrained remainder across the new
        # world — instead of degrading to the epoch-boundary
        # fallback. `resize_report` keeps the newest migration's
        # evidence (old/new world, records reassigned).
        self.migrate_world = bool(migrate_world)
        self.resize_report: Optional[Dict] = None
        self.data_start: Tuple[int, int] = (0, 0)
        self.resume_gap_batches = 0
        self.cursor_fallbacks = 0
        self.snapshot: Optional[TrainSnapshot] = None

    def close(self):
        """Uninstall the signal handlers this trainer installed (a
        no-op for a caller-provided or disabled handler). Without
        this, Ctrl-C after the training loop would only set a stale
        flag instead of interrupting. Idempotent; `with` calls it.

        Note: installing handlers requires the main thread — construct
        with ``install_signals=False`` off the main thread and poll a
        caller-owned handler instead."""
        if self._owns_handler and self.handler is not None:
            self.handler.uninstall()
            self._owns_handler = False

    def __enter__(self) -> "ElasticTrainer":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()

    # -- resume -------------------------------------------------------

    def resume(self, *, like: Any = None, broadcast: bool = False):
        """(state, start_step) from the latest GOOD checkpoint —
        corrupt/partial newest steps are skipped with a warning. On a
        fresh directory returns ``(like, 0)``: the template passes
        through unchanged, so the documented
        ``state, start = trainer.resume(like=state)`` loop works on
        the very first run too. Keeps `like` as the rollback
        template.

        With a `dataset`/`rng`/guard attached, the step's aux sidecar
        is restored too: the data cursor lands in `data_start` (feed
        it to ``dataset.epoch(e, start_batch=b)``), the RNG and guard
        are re-seeded in place, and `resume_gap_batches` is 0 — the
        exactly-once contract. A missing/corrupt/incompatible sidecar
        degrades to the epoch boundary derived from the step count:
        the interrupted epoch replays from batch 0 (`resume_gap_
        batches` counts the replay), `cursor_fallbacks` increments,
        and a `training.cursor_fallback` event names the reason —
        degraded must never mean silent. `snapshot` keeps the full
        `TrainSnapshot` of what was actually reconstructed."""
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.utils import checkpoint as ckpt
        t0 = time.time()
        self._like = like
        self.resume_gap_batches = 0
        self.data_start = (0, 0)
        out = ckpt.restore_latest(self.directory, like=like,
                                  broadcast=broadcast, with_step=True)
        if out is None:
            self.snapshot = None
            return like, 0
        restored, step = out
        step = int(step)
        self._last_good_step = step
        aux, aux_err = ckpt.load_step_aux(self.directory, step)
        needs_aux = self.dataset is not None or self.rng is not None
        if aux is None and not needs_aux:
            # Model-state-only mode (no dataset/rng attached) resuming
            # a checkpoint saved without a sidecar — e.g. a pre-exact-
            # resume directory or a plain save_step caller. There is
            # no cursor to lose: this is the documented PR-2 behavior,
            # not a degraded resume, so no fallback noise.
            aux_err = None
        exact = aux is not None or not needs_aux
        if exact and aux is not None \
                and aux.get("schema") != SNAPSHOT_SCHEMA:
            exact, aux_err = False, (
                f"snapshot schema {aux.get('schema')!r} != supported "
                f"{SNAPSHOT_SCHEMA}")
        if exact and aux is not None and aux.get("step") != step:
            # Sidecar from a different save than the step that
            # restored (e.g. the newest step was corrupt and discovery
            # fell back, or an orphan sidecar from a killed save) —
            # its cursor describes the wrong position.
            exact, aux_err = False, (
                f"snapshot step {aux.get('step')!r} != restored "
                f"step {step}")
        self.resize_report = None
        if exact and aux is not None:
            try:
                if self.dataset is not None:
                    data_state = aux.get("data")
                    if data_state is None:
                        raise ValueError(
                            "snapshot has no data cursor (saved "
                            "without an attached dataset?)")
                    self._restore_data(step, data_state)
                    self.data_start = tuple(self.dataset.cursor)
                if self.rng is not None:
                    if aux.get("rng") is None:
                        # Same contract as the dataset leg: an
                        # attached RNG with no snapshotted stream is
                        # NOT an exact resume — draws would restart
                        # from the fresh seed silently.
                        raise ValueError(
                            "snapshot has no host RNG state (saved "
                            "without an attached rng?)")
                    _rng_restore(self.rng, aux["rng"])
                if aux.get("guard") is not None:
                    self.guard.restore(aux["guard"])
            except (TypeError, ValueError, KeyError) as e:
                # DataStateError is a ValueError: incompatible cursors
                # land here too, with the mismatch named.
                exact, aux_err = False, repr(e)
        gap = 0
        if not exact:
            if self.dataset is not None:
                # Epoch-boundary fallback: derive the epoch from the
                # step count and replay it from batch 0. Degraded but
                # correct-on-epoch-boundaries — and loud.
                spe = max(1, int(self.dataset.steps_per_epoch()))
                epoch, gap = divmod(step, spe)
                self.data_start = (int(epoch), 0)
            self.cursor_fallbacks += 1
            _obs_catalog.resilience_metrics()["cursor_fallbacks"].inc()
            _events.emit("training.cursor_fallback", step=step,
                         reason=str(aux_err), gap_batches=int(gap))
            sys.stderr.write(
                f"horovod_tpu: exact-resume cursor unavailable at step "
                f"{step} ({aux_err}); resuming from the epoch boundary "
                f"— {gap} batch(es) of the interrupted epoch will "
                f"replay\n")
        self.resume_gap_batches = int(gap)
        recovery_s = time.time() - t0
        met = _obs_catalog.resilience_metrics()
        met["resumes"].inc()
        met["resume_gap"].set(float(gap))
        met["train_recovery"].observe(recovery_s)
        _events.emit(
            "training.resume", step=step, exact=bool(exact),
            epoch=int(self.data_start[0]), batch=int(self.data_start[1]),
            gap_batches=int(gap),
            recovery_ms=round(recovery_s * 1e3, 3))
        self.snapshot = TrainSnapshot(
            state=restored, step=step,
            data_state=(aux or {}).get("data"),
            rng_state=(aux or {}).get("rng"),
            guard_state=(aux or {}).get("guard"),
            exact=bool(exact), gap_batches=int(gap))
        return restored, step

    def _restore_data(self, step: int, data_state: Dict) -> None:
        """The dataset leg of an exact resume. Plain restore first;
        with `migrate_world` on, a cursor whose ONLY incompatibility
        is its (rank, world) identity is migrated instead — the
        elastic-resize path: the dataset rebalances the interrupted
        epoch's untrained remainder across the current world
        (`ShardedDataset.restore(migrate=True)`), which is still an
        EXACT resume (gap 0 — nothing replayed, nothing skipped; the
        union over ranks is pinned by the resize equivalence
        harness). Any other mismatch re-raises into the loud
        epoch-boundary fallback as before."""
        from horovod_tpu.data import DataStateError
        try:
            self.dataset.restore(data_state)
            return
        except DataStateError:
            if not self.migrate_world:
                raise
        t0 = time.time()
        # Raises DataStateError itself when more than the world
        # identity mismatches — the caller's fallback handles it.
        self.dataset.restore(data_state, migrate=True)
        rebalance = getattr(self.dataset, "last_rebalance", None)
        if rebalance is None:
            # Same-world rank relabel (streams are slot-indexed —
            # rank k's suffix continues unchanged): an exact resume,
            # not a resize. No rebalance happened, so no resize
            # event/metrics — they would read as phantom resizes.
            return
        report = dict(rebalance)
        report["step"] = int(step)
        report["rebalance_s"] = round(time.time() - t0, 6)
        self.resize_report = report
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.obs import flightrec as _flightrec
        m = _obs_catalog.elastic_metrics()
        m["rebalance"].observe(report["rebalance_s"])
        m["records_reassigned"].inc(
            int(report.get("records_reassigned", 0)))
        _events.emit(
            "training.resize", step=int(step),
            old_world=report.get("old_world"),
            new_world=report.get("new_world"),
            rank=int(getattr(self.dataset, "rank", -1)),
            epoch=report.get("epoch"),
            from_batch=report.get("from_batch"),
            records_reassigned=report.get("records_reassigned"),
            rebalance_ms=round(report["rebalance_s"] * 1e3, 3))
        _flightrec.trigger(
            "training.resize", step=int(step),
            old_world=report.get("old_world"),
            new_world=report.get("new_world"))
        sys.stderr.write(
            f"horovod_tpu: elastic resize at step {step} — world "
            f"{report.get('old_world')} -> {report.get('new_world')}, "
            f"{report.get('records_reassigned')} record(s) of epoch "
            f"{report.get('epoch')} rebalanced\n")

    # -- the per-step hook --------------------------------------------

    def _snapshot_aux(self, step: int) -> Dict:
        """The aux sidecar for one save: everything exactly-once needs
        beyond the pytree. Cheap (a handful of scalars + the guard
        window), so it's built fresh at every save."""
        aux: Dict[str, Any] = {"schema": SNAPSHOT_SCHEMA,
                               "step": int(step),
                               "guard": self.guard.state()}
        if self.dataset is not None:
            aux["data"] = self.dataset.state()
        if self.rng is not None:
            aux["rng"] = _rng_state(self.rng)
        return aux

    def after_step(self, step: int, state: Any, loss) -> Any:
        """Fold one finished step into the resilience machinery; see
        class docstring. Returns the state the loop should continue
        from (the rolled-back one after a NaN/spike trip)."""
        from horovod_tpu.resilience import chaos
        if chaos.fires("train_crash"):
            # Simulated process death at the worst mid-epoch point:
            # the step's work is done but nothing is checkpointed yet
            # — the equivalence harness's kill-mid-epoch scenario.
            raise chaos.ChaosError(
                f"injected training-process kill after step {step} "
                f"(site train_crash)")
        if self.guard.check(loss):
            # No emergency save needed even if a preemption signal
            # landed this same step: the rolled-back state IS the last
            # good checkpoint, already durable on disk — the diverged
            # steps since it are precisely what must not be saved.
            return self._rollback(step, float(loss))
        if (self.handler is not None and self.handler.triggered
                and not self._emergency_done):
            self._emergency_save(step, state)
            return state
        # Deliberately NOT gated on the preemption flag: a loop that
        # chooses to keep training after the signal must keep its
        # periodic checkpoints.
        if self.save_every > 0 and step % self.save_every == 0:
            from horovod_tpu.utils import checkpoint as ckpt
            ckpt.save_step(self.directory, step, state,
                           keep=self.keep, block=self.block,
                           retry=self.retry,
                           aux=self._snapshot_aux(step))
            self._last_good_step = step
        return state

    @property
    def should_stop(self) -> bool:
        return self.handler is not None and self.handler.triggered

    def _rollback(self, step: int, loss: float) -> Any:
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.utils import checkpoint as ckpt
        self.rollbacks += 1
        out = ckpt.restore_latest(self.directory, like=self._like,
                                  with_step=True)
        if out is None:
            raise FloatingPointError(
                f"step {step}: non-finite/spiking loss ({loss}) with "
                f"no checkpoint to roll back to in {self.directory}")
        restored, good_step = out
        # The restore may have fallen back PAST what we last wrote
        # (that checkpoint could itself be the corrupt one).
        self._last_good_step = good_step
        _obs_catalog.resilience_metrics()["rollbacks"].inc()
        _events.emit("training.rollback", step=step, loss=loss,
                     restored_step=int(good_step))
        # Post-mortem capture (obs/flightrec.py, no-op unless
        # HVD_FLIGHT_DIR is set): a divergence rollback is exactly the
        # incident whose run-up (loss stream, chaos fires, step
        # cadence) the bundle preserves.
        from horovod_tpu.obs import flightrec as _flightrec
        _flightrec.trigger("training.rollback", step=step,
                           loss=float(loss),
                           restored_step=int(good_step))
        sys.stderr.write(
            f"horovod_tpu: step {step} diverged (loss={loss}); rolled "
            f"back to checkpoint step {good_step} "
            f"(rollback #{self.rollbacks})\n")
        return restored

    def _emergency_save(self, step: int, state: Any):
        """Synchronous (the process is about to die — an async write
        would race teardown), once."""
        if self._emergency_done:
            return
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.utils import checkpoint as ckpt
        ckpt.wait_pending()
        ckpt.save_step(self.directory, step, state, keep=self.keep,
                       block=True, retry=self.retry,
                       aux=self._snapshot_aux(step))
        self._last_good_step = step
        self._emergency_done = True
        _obs_catalog.resilience_metrics()["emergency_saves"].inc()
        _events.emit(
            "training.emergency_save", step=step,
            signum=getattr(self.handler, "signum", None))
        sys.stderr.write(
            f"horovod_tpu: preemption signal "
            f"{getattr(self.handler, 'signum', None)} — emergency "
            f"checkpoint at step {step} in {self.directory}\n")
