"""Chaos injection: deterministic, replayable faults at named sites.

The subsystems this repo claims are robust (checkpoint I/O, the train
step, collectives, the serving engine) are instrumented with *sites* —
single-line hooks of the form::

    from horovod_tpu.resilience import chaos
    if chaos.fires("ckpt_write_fail"):
        raise chaos.ChaosError("injected checkpoint write failure")

A site costs one module-global load and a ``None`` check when chaos is
disarmed (the common case), so production paths pay nothing
measurable. When a `ChaosMonkey` is installed — programmatically or
via the ``HVD_CHAOS`` environment variable — sites fire according to
their armed spec, and every fire is counted so tests can assert the
fault actually happened.

Spec grammar (comma-separated sites)::

    HVD_CHAOS="ckpt_write_fail:2,collective_slow:1:delay=0.5"
    HVD_CHAOS="serving_tick_stall:1:delay=2:p=0.5"  HVD_CHAOS_SEED=7

``site:count`` fires on the first ``count`` opportunities
(``count=-1`` = every opportunity); ``p=<float>`` makes each
opportunity fire with that probability from a per-site RNG seeded by
``HVD_CHAOS_SEED`` ^ hash(site) — the same seed replays the same
fault schedule; ``delay=<seconds>`` parameterizes slow/hang sites.

Instrumented sites (docs/resilience.md has the full table):

======================  ==================================================
site                    instrumented at
======================  ==================================================
ckpt_write_fail         `utils/checkpoint.py::save` (each write attempt)
ckpt_kill               `utils/checkpoint.py::save_step` — process
                        death DURING a save: after the staging write,
                        before the atomic rename (no discoverable step)
train_crash             `resilience/elastic.py::after_step` — process
                        death mid-epoch: the step's work is done,
                        nothing checkpointed yet
data_read_fail          `data/__init__.py` shard open, read mode
data_write_fail         `data/__init__.py` shard open, write mode
collective_slow         `ops/collectives.py` op entry (host-side; under
                        jit this fires at trace/dispatch time)
step_exception          `models/train.py` step invocation
grad_nan                `models/train.py` step result (NaNs loss+params)
serving_dispatch_crash  `serving/engine.py` dispatch-loop top
serving_tick_stall      `serving/scheduler.py` inside the tick bracket
                        (cooperative: ends early once abandoned)
serving_deadline_storm  `serving/scheduler.py` — expires every queued
                        request's deadline at once
router.replica_kill     `serving/router.py` monitor sweep — hard-kills
                        the busiest replica (no drain)
======================  ==================================================

The authoritative site list is GENERATED from source (`scan_sites` /
`site_table_md` below — docs/resilience.md's table is written by
``python -m horovod_tpu.analysis --write-chaos-table`` and drift-pinned
by a test), so a new site cannot ship undocumented.
"""

from __future__ import annotations

import contextlib
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from horovod_tpu.runtime.config import env_int, env_str


class ChaosError(RuntimeError):
    """The exception injected faults raise — typed so recovery code
    (and tests) can target injected failures without catching real
    programming errors by accident."""


@dataclass
class _Site:
    name: str
    count: int = 1               # fires remaining; -1 = unbounded
    prob: float = 1.0            # per-opportunity fire probability
    delay: float = 0.0           # seconds, for slow/hang sites
    fired: int = 0               # fires so far
    seen: int = 0                # opportunities so far
    rng: random.Random = field(default_factory=random.Random)


class ChaosMonkey:
    """A set of armed sites. Thread-safe: sites fire from submit
    threads, the serving dispatch thread, and training loops alike."""

    def __init__(self, spec: str = "", *, seed: int = 0):
        self._seed = seed
        self._lock = threading.Lock()
        self._sites: Dict[str, _Site] = {}
        if spec:
            self.arm_spec(spec)

    def arm(self, site: str, count: int = 1, *, prob: float = 1.0,
            delay: float = 0.0) -> "ChaosMonkey":
        """Arm `site` to fire `count` times (-1 = always), each
        opportunity firing with probability `prob`. Returns self so
        arms chain."""
        import zlib
        with self._lock:
            s = _Site(site, count=count, prob=prob, delay=delay)
            # Deterministic per-site stream: same seed ⇒ same schedule,
            # independent of what other sites consume. crc32, not
            # hash() — str hashing is salted per process and must not
            # change the replayed fault schedule.
            s.rng.seed((self._seed << 16)
                       ^ zlib.crc32(site.encode()))
            self._sites[site] = s
        return self

    def arm_spec(self, spec: str) -> "ChaosMonkey":
        """Parse and arm an ``HVD_CHAOS``-style spec string. Malformed
        fields raise a `ValueError` naming the offending part — a
        typo'd spec must fail loudly and legibly, not as a bare
        float() traceback at import."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            name = fields[0]
            count, prob, delay = 1, 1.0, 0.0
            for f in fields[1:]:
                try:
                    if f.startswith("p="):
                        prob = float(f[2:])
                    elif f.startswith("delay="):
                        delay = float(f[6:])
                    else:
                        count = int(f)
                except ValueError:
                    raise ValueError(
                        f"bad chaos spec field {f!r} in {part!r} "
                        f"(grammar: site:count[:p=<float>]"
                        f"[:delay=<seconds>])") from None
            self.arm(name, count, prob=prob, delay=delay)
        return self

    def fires(self, site: str) -> bool:
        """One opportunity at `site`: True when the armed fault should
        trigger now (and consumes one fire)."""
        with self._lock:
            s = self._sites.get(site)
            if s is None:
                return False
            s.seen += 1
            if s.count == 0:
                return False
            if s.prob < 1.0 and s.rng.random() >= s.prob:
                return False
            if s.count > 0:
                s.count -= 1
            s.fired += 1
            return True

    def delay_of(self, site: str, default: float = 1.0) -> float:
        with self._lock:
            s = self._sites.get(site)
            return default if s is None or s.delay <= 0 else s.delay

    def fired(self, site: str) -> int:
        with self._lock:
            s = self._sites.get(site)
            return 0 if s is None else s.fired

    def counts(self) -> Dict[str, int]:
        """{site: fires so far} — the test/bench assertion surface."""
        with self._lock:
            return {n: s.fired for n, s in self._sites.items()}

    def disarm(self, site: Optional[str] = None):
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)


# The module-level switch every site checks. None ⇒ disabled ⇒ a site
# is one global load + `is None`.
_active: Optional[ChaosMonkey] = None


def install(monkey: Optional[ChaosMonkey]) -> Optional[ChaosMonkey]:
    """Install (or with None, remove) the process-global monkey."""
    global _active
    _active = monkey
    return monkey


def active() -> Optional[ChaosMonkey]:
    return _active


def _record_fire(site: str):
    """Observability for a fired fault (docs/observability.md): the
    per-site ``hvd_resilience_faults_injected_total`` counter and a
    structured event. Only runs on the (rare) fire path, so the
    zero-overhead-when-disarmed contract of `fires` is untouched."""
    from horovod_tpu.obs import catalog as _obs_catalog
    from horovod_tpu.obs import events as _events
    from horovod_tpu.obs import flightrec as _flightrec
    _obs_catalog.resilience_metrics()["faults_injected"].inc(
        site=site)
    _events.emit("chaos.fire", site=site)
    # A chaos fire is an incident by construction — capture the state
    # the fault lands in (no-op unless HVD_FLIGHT_DIR is set). The
    # chaos.fire event above is in the ring BEFORE the dump, so the
    # bundle's newest event names its own trigger.
    _flightrec.trigger("chaos.fire", site=site)


def fires(site: str) -> bool:
    """The zero-overhead-when-disabled site hook."""
    m = _active
    if m is None:
        return False
    hit = m.fires(site)
    if hit:
        _record_fire(site)
    return hit


def slow_site(site: str, default_delay: float = 1.0) -> bool:
    """The shared slow/hang site body: when `site` fires, block the
    calling thread for its armed ``delay`` (modeling a host parked on
    a dead peer's rendezvous). Returns whether it fired. Same
    zero-overhead shape as `fires` when disarmed."""
    m = _active
    if m is None or not m.fires(site):
        return False
    _record_fire(site)
    import time
    time.sleep(m.delay_of(site, default_delay))
    return True


def delay_of(site: str, default: float = 1.0) -> float:
    m = _active
    return default if m is None else m.delay_of(site, default)


def fired(site: str) -> int:
    m = _active
    return 0 if m is None else m.fired(site)


def arm(site: str, count: int = 1, *, prob: float = 1.0,
        delay: float = 0.0) -> ChaosMonkey:
    """Arm one site on the installed monkey (installing a fresh one if
    chaos was disabled) — the programmatic entry bench.py uses."""
    m = _active or install(ChaosMonkey(seed=_env_seed()))
    return m.arm(site, count, prob=prob, delay=delay)


@contextlib.contextmanager
def armed(spec: str, *, seed: int = 0):
    """Test scoping: install a monkey for the with-block, restore the
    previous one (usually None) after::

        with chaos.armed("ckpt_write_fail:2") as monkey:
            ...
        assert monkey.fired("ckpt_write_fail") == 2
    """
    prev = _active
    monkey = ChaosMonkey(spec, seed=seed)
    install(monkey)
    try:
        yield monkey
    finally:
        install(prev)


# ---------------------------------------------------------------------------
# The generated site table (docs/resilience.md). `_SITE_DOCS` holds the
# one-line fault model per site; WHERE each site is instrumented is
# scanned from source, so the docs table cannot drift from the code —
# a site added without a `_SITE_DOCS` entry fails the drift test, and a
# `_SITE_DOCS` entry whose site no longer exists is dropped from the
# table (and fails the test too).
# ---------------------------------------------------------------------------

_SITE_DOCS: Dict[str, str] = {
    "ckpt_write_fail": "checkpoint I/O failure (GCS 5xx, ENOSPC)",
    "ckpt_kill": "process death DURING a save — after the staging "
                 "write, before the atomic rename",
    "train_crash": "process death mid-epoch — step done, nothing "
                   "checkpointed yet",
    "data_read_fail": "input-pipeline shard-open fault (read mode)",
    "data_write_fail": "dataset-write shard-open fault "
                       "(`write_shards`)",
    "collective_slow": "slow/hung collective (dead peer rendezvous)",
    "step_exception": "worker exception mid-step",
    "grad_nan": "NaN gradients poisoning loss+params",
    "serving_dispatch_crash": "serving dispatch thread dies",
    "serving_tick_stall": "hung decode tick (cooperative: ends early "
                          "once abandoned)",
    "serving_deadline_storm": "every queued request's deadline "
                              "expires at once",
    "router.replica_kill": "abrupt replica death mid-stream — the "
                           "router must migrate its in-flight "
                           "requests token-exactly",
    "rank_death": "training rank dies mid-epoch (preemption/crash): "
                  "heartbeat lease lapses, survivors must resize and "
                  "rebalance shards",
    "rank_join": "a new rank announces itself mid-run — the world "
                 "must grow with a new generation",
    "heartbeat_drop": "a heartbeat write is lost in transit — lease "
                      "math must tolerate isolated misses without a "
                      "false death",
    "kv_drop": "a rendezvous-KV round-trip is lost in transit — the "
               "shared RetryPolicy must absorb isolated drops "
               "(typed KVTransportError on exhaustion)",
    "kv_delay": "a slow rendezvous-KV round-trip (congested "
                "coordinator) — leases must tolerate it",
    "kv_partition": "ASYMMETRIC partition: this process's KV writes "
                    "stop landing while reads still work — the "
                    "minority member must adopt the commit that "
                    "excludes it and exit MembershipError, never "
                    "split-brain at the old generation",
    "disagg.block_corrupt": "a transferred KV block's bytes flip in "
                            "flight (prefill->decode handoff) — the "
                            "byte-digest verify must reject the "
                            "graft and the stream fall back to "
                            "token-level recompute, bitwise-exact",
    "serving.overload_storm": "overload storm: every known tenant "
                              "escalates one brownout rung per "
                              "firing (hedging off -> spec-k capped "
                              "-> lowest-priority streams "
                              "preempted) — degradation must be "
                              "graduated and per-tenant, never a "
                              "fleet-wide 503",
}

_SITE_CALL_RE = (r'(?:chaos\s*\.\s*)?(?:fires|slow_site)\(\s*'
                 r'[\'"]([\w.]+)[\'"]')

# Sites whose name is BUILT at runtime (the literal-call regex cannot
# see them); only these get the quoted-name fallback in `scan_sites` —
# scanning every documented name would let a mere mention of another
# site in a hook-calling file fabricate an "instrumented in" row.
_VARIABLE_SITES = ("data_read_fail", "data_write_fail")


def scan_sites(root: Optional[str] = None) -> Dict[str, list]:
    """{site: sorted relative paths that instrument it}, scanned from
    the package source: literal ``chaos.fires("x")`` /
    ``chaos.slow_site("x")`` calls, plus — for documented sites whose
    name is built at runtime (the data read/write pair) — quoted
    occurrences of the site name in files that call the hooks."""
    import os
    import re
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    me = os.path.abspath(__file__)
    sources = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.abspath(path) == me:
                continue   # this module's own docs/defs are not sites
            with open(path, "r", encoding="utf-8") as f:
                sources[os.path.relpath(path, root)] = f.read()
    out: Dict[str, list] = {}
    for rel, text in sources.items():
        for site in re.findall(_SITE_CALL_RE, text):
            out.setdefault(site, set()).add(rel)
        if "chaos.fires(" in text or "chaos.slow_site(" in text:
            for site in _VARIABLE_SITES:
                if f'"{site}"' in text or f"'{site}'" in text:
                    out.setdefault(site, set()).add(rel)
    return {site: sorted(files) for site, files in sorted(out.items())}


def site_table_md() -> str:
    """The chaos-site table as GitHub markdown — the generated section
    of docs/resilience.md (``python -m horovod_tpu.analysis
    --write-chaos-table``; a drift test pins the doc to this exact
    output). Undocumented scanned sites render loudly so the drift
    test, not a reader, catches them first."""
    rows = ["| site | instrumented in | fault modeled |",
            "| --- | --- | --- |"]
    for site, files in scan_sites().items():
        doc = _SITE_DOCS.get(
            site, "(UNDOCUMENTED — add to chaos._SITE_DOCS)")
        where = ", ".join(f"`horovod_tpu/{f}`" for f in files)
        rows.append(f"| `{site}` | {where} | {doc} |")
    return "\n".join(rows) + "\n"


def _env_seed() -> int:
    return env_int("HVD_CHAOS_SEED", 0)


def _init_from_env():
    """Arm from ``HVD_CHAOS`` at import — how subprocess runs (the CI
    chaos smoke, hvdrun workers) get their faults. A malformed spec
    fails the import loudly with the offending field named (chaos
    that silently fails to arm would let a broken resilience drill
    pass green)."""
    spec = env_str("HVD_CHAOS")
    if spec:
        try:
            install(ChaosMonkey(spec, seed=_env_seed()))
        except ValueError as e:
            raise ValueError(
                f"HVD_CHAOS={spec!r}: {e}") from None


_init_from_env()
