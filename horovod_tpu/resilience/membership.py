"""Elastic training membership: who is in the world, and what happens
when that changes (docs/resilience.md "Elastic membership").

Horovod's launch contract is a fixed ``mpirun -np N`` world — one rank
dying kills the job (the reference's only answer is the 60 s stall
warning). On preemptible TPU fleets that is exactly backwards: rank
death is scheduled, and MLPerf-scale runs treat restart/resume as
first-class (arXiv:1909.09756). This module gives training the
membership story serving got from the router (PR 9):

* `WorldMonitor` — a heartbeat **lease** per member over a small KV
  transport (`InProcessKV` for the CPU-simulated worlds tests run;
  `install_kv` plugs a real rendezvous backend the same way
  `obs.straggler.install_exchange` plugs a real allgather; the native
  bootstrap KV from `runtime/bootstrap.py` is the deployment target).
  A member whose newest heartbeat is older than ``HVD_LEASE_S`` is
  dead; a ``join/<member>`` announcement is a prospective member.
* The **resize protocol** — a barrier'd agreement: any member that
  detects a death/join proposes the next *generation* (monotonic,
  `hvd_elastic_generation`) with the deterministic survivor list;
  every proposed member acks; the fully-acked proposal commits the
  new ``(world, rank)`` assignment (survivors ordered by old rank,
  joiners appended). Every member then rolls back to the last
  committed `TrainSnapshot`, re-keys the runtime
  (`bootstrap.apply_resize` — generation bump + membership fields +
  eager-op cache drop), and rebalances its shard stream
  (`ShardedDataset.restore(migrate=True)` via the `ElasticTrainer`
  resize path).
* `SimulatedWorld` — the in-process N-thread elastic training world
  CPU tests and the equivalence harness drive end-to-end: real
  heartbeats, real lease expiry, a gradient-averaging lockstep loop,
  and the chaos sites that make the drills honest — ``rank_death``
  (a member stops heartbeating mid-epoch), ``rank_join`` (a new
  member announces itself after a shrink), ``heartbeat_drop`` (a
  beat is lost in transit; the lease must tolerate it).

The determinism contract the whole stack leans on: given the KV's
committed history, every member computes the SAME assignment, the
same generation, and (through `data.remainder_after`) the same record
partition — so the union of all ranks' post-resize batches is exactly
the untrained remainder of the interrupted epoch.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from horovod_tpu.resilience import chaos
from horovod_tpu.resilience import detector as _detector
from horovod_tpu.resilience.retry import RetryError, RetryPolicy
from horovod_tpu.runtime.config import env_float, env_int

from horovod_tpu.analysis import lockcheck


class MembershipError(RuntimeError):
    """This member cannot continue in the world — typically it was
    declared dead by the others (its lease lapsed while it was
    paused/partitioned) and a newer generation excludes it. The only
    safe answer is to stop and re-join as a fresh member."""


class KVTransportError(MembershipError):
    """A rendezvous-KV round-trip failed even after the shared
    `RetryPolicy` ran dry — the typed answer to what used to surface
    as a raw socket error out of the heartbeat thread. Consumers
    degrade: a heartbeat counts a missed beat, the watch loop skips a
    tick, the resize protocol times out into `MembershipError`."""


class _KVFault(OSError):
    """One failed KV attempt (transport down, chaos ``kv_drop`` /
    ``kv_partition``) — an `OSError` so the shared `RetryPolicy`
    retries it as transient; `KVTransportError` is what escapes once
    the policy gives up."""


def _kv_policy() -> RetryPolicy:
    """The KV transport's retry schedule: `HVD_IO_RETRIES` attempts
    (the same knob checkpoint/data I/O honor) with a tighter base
    delay — membership traffic is latency-sensitive (heartbeats race
    leases)."""
    return RetryPolicy(max_attempts=max(1, env_int("HVD_IO_RETRIES", 3)),
                       base_delay_s=0.02, max_delay_s=0.25)


def _kv_chaos(op: str) -> None:
    """The KV transport-fault chaos sites, applied to every hardened
    round-trip (docs/resilience.md chaos-site table):

    * ``kv_drop`` — this round-trip is lost in transit (both
      directions); the retry policy must absorb isolated drops.
    * ``kv_partition`` — ASYMMETRIC partition: writes from this
      process stop landing while reads still work, the nastiest
      split-brain shape — the minority member keeps seeing a live
      world it can no longer prove itself alive to, and must exit
      `MembershipError` once a commit excludes it.
    * ``kv_delay`` — a slow round-trip (congested rendezvous);
      leases must tolerate it.
    """
    if chaos.fires("kv_drop"):
        raise _KVFault(f"chaos kv_drop: {op} round-trip lost")
    if op == "put" and chaos.fires("kv_partition"):
        raise _KVFault(f"chaos kv_partition: {op} did not land "
                       f"(asymmetric write partition)")
    chaos.slow_site("kv_delay", 0.05)


def _hardened_call(policy: RetryPolicy, op: str, attempt: Callable, *,
                   on_retry: Optional[Callable] = None,
                   what: str = "KV"):
    """The BootstrapKV/ChaosKV common core — one hardened round-trip:
    the ``kv_*`` chaos sites + the shared `RetryPolicy` + typed
    `KVTransportError` exhaustion. ``on_retry`` is the transport's
    between-attempts hook (BootstrapKV reconnects + logs there)."""
    def one():
        _kv_chaos(op)
        return attempt()

    try:
        return policy.call(
            one, on_retry=on_retry if on_retry is not None
            else (lambda *_: None))
    except RetryError as e:
        raise KVTransportError(
            f"{what} {op} failed after {e.attempts} attempt(s): "
            f"{e.__cause__!r}") from e


# ---------------------------------------------------------------------------
# KV transport.
# ---------------------------------------------------------------------------

class InProcessKV:
    """Dict-backed KV with the 4 primitives the protocol needs —
    the CPU test double for the rendezvous server. Thread-safe;
    values are plain JSON-able objects (stored by reference, so
    writers must not mutate after put)."""

    def __init__(self):
        self._lock = lockcheck.register(
            "InProcessKV._lock", threading.Lock())
        self._d: Dict[str, Any] = {}

    def put(self, key: str, value) -> None:
        with self._lock:
            self._d[key] = value

    def put_if_absent(self, key: str, value):
        """Atomic first-write-wins; returns the winning value."""
        with self._lock:
            return self._d.setdefault(key, value)

    def get(self, key: str):
        with self._lock:
            return self._d.get(key)

    def scan(self, prefix: str) -> Dict[str, Any]:
        with self._lock:
            return {k: v for k, v in self._d.items()
                    if k.startswith(prefix)}

    def delete(self, key: str) -> None:
        with self._lock:
            self._d.pop(key, None)


class BootstrapKV:
    """Adapter over the launcher's rendezvous KV plane
    (`runtime/bootstrap.py` / `native.bindings.kv_set/kv_get`) — the
    deployment transport for multi-controller worlds; JSON values.

    Every round-trip is HARDENED: the `kv_drop`/`kv_delay`/
    `kv_partition` chaos sites model transport faults, each attempt
    runs under the shared `RetryPolicy` (``HVD_IO_RETRIES``), a
    failed round-trip tries a rendezvous RECONNECT between attempts
    (the server restarting, a flapped link), and exhaustion raises
    the typed `KVTransportError` — never a raw socket error out of
    the heartbeat thread.

    Capability notes, honest by design: the native plane has no scan
    and no compare-and-swap. Neither breaks the protocol —
    `put_if_absent` degrades to read-then-write, which is benign
    because proposal and commit CONTENTS are deterministic functions
    of the committed history (two racing writers write identical
    bytes, and the single-threaded rendezvous server serializes
    them); join discovery, the one genuinely scan-shaped read, rides
    the well-known ``join_queue`` key instead (`scan` raises, and
    `WorldMonitor.joiners()` falls back). Heartbeats, death
    detection, and the whole shrink path are targeted gets.

    One honest ambiguity: the native ``kv_get`` answers None for
    both "key absent" and "server unreachable". A miss inside
    ``_TRUST_WINDOW_S`` of the last successful round-trip is trusted
    as absent (protocol probes miss constantly — pinging per miss
    would double the traffic); a miss outside it is verified with a
    ``ping`` and escalates to reconnect-and-retry when the transport
    is actually down."""

    _TRUST_WINDOW_S = 1.0

    def __init__(self, native=None, *,
                 policy: Optional[RetryPolicy] = None):
        if native is None:
            from horovod_tpu.runtime import state as _rt_state
            native = _rt_state.global_state().native
        if native is None:
            raise MembershipError(
                "BootstrapKV needs the native control plane "
                "(rendezvous client); init under hvdrun with "
                "HOROVOD_KV set, or install an InProcessKV/"
                "custom transport via membership.install_kv")
        self._native = native
        self._policy = policy if policy is not None else _kv_policy()
        self._lock = lockcheck.register(
            "BootstrapKV._lock", threading.Lock())
        self._last_ok_t = float("-inf")
        self.reconnects = 0

    # -- transport plumbing -------------------------------------------

    def _mark_ok(self):
        with self._lock:
            self._last_ok_t = time.monotonic()

    def _recently_ok(self) -> bool:
        with self._lock:
            return (time.monotonic() - self._last_ok_t
                    < self._TRUST_WINDOW_S)

    def _reconnect(self):
        """Best-effort rendezvous reconnect between retry attempts
        (HOROVOD_KV names the server)."""
        from horovod_tpu.runtime.config import env_str
        addr = env_str("HOROVOD_KV")
        if not addr or ":" not in addr:
            return
        host, port = addr.rsplit(":", 1)
        with self._lock:
            self.reconnects += 1
        try:
            self._native.connect(host, int(port), timeout_s=2.0)
        except (OSError, ValueError, RuntimeError):
            pass   # next attempt will fault again and re-enter here

    def _call(self, op: str, attempt: Callable):
        """One hardened round-trip: chaos sites + retry policy +
        reconnect between attempts; typed exhaustion."""
        def on_retry(exc, n, delay):
            import sys
            self._reconnect()
            sys.stderr.write(
                f"horovod_tpu membership: transient KV fault "
                f"({exc!r}); retry {n} in {delay:.2f}s\n")

        return _hardened_call(self._policy, op, attempt,
                              on_retry=on_retry, what="rendezvous KV")

    # -- the KV surface -----------------------------------------------

    def put(self, key: str, value) -> None:
        import json
        payload = json.dumps(value).encode()

        def attempt():
            if not self._native.kv_set(key, payload):
                raise _KVFault(f"kv_set({key!r}) did not land")
            self._mark_ok()

        self._call("put", attempt)

    def get(self, key: str):
        import json

        def attempt():
            raw = self._native.kv_get(key, timeout_ms=0)
            if raw is None:
                # Absent vs unreachable: trust a recent success,
                # otherwise verify the server actually answers.
                if not self._recently_ok():
                    try:
                        alive = self._native.ping()
                    except (OSError, RuntimeError):
                        alive = False
                    if not alive:
                        raise _KVFault(
                            f"kv_get({key!r}): rendezvous "
                            f"unreachable")
                    self._mark_ok()
                return None
            self._mark_ok()
            try:
                return json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                return None

        return self._call("get", attempt)

    def put_if_absent(self, key: str, value):
        cur = self.get(key)
        if cur is not None:
            return cur
        self.put(key, value)
        return self.get(key)

    def scan(self, prefix: str) -> Dict[str, Any]:
        raise NotImplementedError(
            "the bootstrap KV plane has no scan; join discovery "
            "uses the join_queue key")

    def delete(self, key: str) -> None:
        # The rendezvous plane has no delete; an empty tombstone is
        # indistinguishable from absent for every protocol read.
        self.put(key, None)


class ChaosKV:
    """The same transport hardening `BootstrapKV` applies to the
    native plane, composable around ANY membership KV (typically
    `InProcessKV`): every round-trip passes the `kv_drop`/`kv_delay`/
    `kv_partition` chaos sites under the shared `RetryPolicy`, with
    typed `KVTransportError` exhaustion — how in-process worlds drill
    transport faults (a partitioned member wraps only ITS handle;
    the survivors' handles stay clean)."""

    def __init__(self, inner, *, policy: Optional[RetryPolicy] = None):
        self._inner = inner
        self._policy = policy if policy is not None else _kv_policy()

    def _call(self, op: str, fn: Callable, *args):
        return _hardened_call(self._policy, op, lambda: fn(*args))

    def put(self, key: str, value) -> None:
        self._call("put", self._inner.put, key, value)

    def get(self, key: str):
        return self._call("get", self._inner.get, key)

    def put_if_absent(self, key: str, value):
        return self._call("put", self._inner.put_if_absent, key,
                          value)

    def scan(self, prefix: str) -> Dict[str, Any]:
        return self._call("get", self._inner.scan, prefix)

    def delete(self, key: str) -> None:
        self._call("put", self._inner.delete, key)


# The pluggable transport, `straggler.install_exchange`-style: None
# means each WorldMonitor constructed without an explicit `kv` gets
# the process-local InProcessKV below (single-process worlds); a
# multi-controller launch installs an adapter over its rendezvous
# service once, before monitors are built.
_KV: Optional[Any] = None
_KV_LOCK = lockcheck.register(
    "membership._KV_LOCK", threading.Lock())


def install_kv(kv: Optional[Any]) -> Optional[Any]:
    """Install (or with None, remove) the process-global membership
    transport; returns the previous one (scoped-swap test pattern)."""
    global _KV
    with _KV_LOCK:
        prev, _KV = _KV, kv
        return prev


def default_kv():
    """The installed transport, or a lazily-created process-local
    `InProcessKV`."""
    global _KV
    with _KV_LOCK:
        if _KV is None:
            _KV = InProcessKV()
        return _KV


# A member whose beat age crosses this fraction of the lease is
# SUSPECT (drained by consumers that can drain; the resize protocol
# ignores suspicion — only DEAD, age past the full lease, resizes).
SUSPECT_LEASE_FRACTION = 0.5

# Process-unique monitor ids for detector-peer namespacing (observer-
# scoped: each member judges its peers through its own clock and KV
# handle; id(self) would alias after garbage collection).
_MONITOR_IDS = itertools.count()


# ---------------------------------------------------------------------------
# The resize decision.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ResizeDecision:
    """One committed generation: the agreed world and this member's
    place in it."""

    generation: int
    world: int
    rank: int                    # THIS member's new rank
    members: List[str]           # rank order (index == rank)
    died: List[str]
    joined: List[str]

    @property
    def kind(self) -> str:
        if self.died and not self.joined:
            return "shrink"
        if self.joined and not self.died:
            return "grow"
        return "shrink" if len(self.died) > len(self.joined) else (
            "grow" if len(self.joined) > len(self.died) else "steady")


def _default_members(world: int) -> List[str]:
    return [f"rank{i}" for i in range(world)]


class WorldMonitor:
    """Heartbeat lease + rank-death/join detection + the barrier'd
    resize protocol, for one member.

    Key space (per shared KV): ``hb/<member>`` heartbeat stamps,
    ``join/<member>`` join announcements, ``prop/<gen>`` the first
    detector's deterministic membership proposal, ``ack/<gen>/<m>``
    the barrier, ``commit/<gen>`` the agreed assignment. Generations
    are monotonic; ``commit/0`` is the launch world (written
    first-wins by whichever founding member gets there first).
    """

    def __init__(self, member_id: Optional[str] = None, *,
                 rank: Optional[int] = None,
                 world: Optional[int] = None,
                 kv: Optional[Any] = None,
                 initial_members: Optional[Sequence[str]] = None,
                 lease_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_change: Optional[Callable[[], None]] = None,
                 joining: bool = False,
                 apply_runtime: bool = True):
        if lease_s is None:
            lease_s = env_float("HVD_LEASE_S", 2.0)
        if heartbeat_s is None:
            heartbeat_s = env_float("HVD_HEARTBEAT_S", lease_s / 4.0)
        if not joining and (rank is None or world is None):
            raise ValueError(
                "a founding member needs rank= and world= "
                "(pass joining=True to announce a new member instead)")
        self.member_id = member_id if member_id is not None else (
            f"rank{rank}" if not joining else "joiner")
        self.kv = kv if kv is not None else default_kv()
        self.lease_s = float(lease_s)
        self.heartbeat_s = float(heartbeat_s)
        self.clock = clock
        self.on_change = on_change
        self._lock = lockcheck.register(
            "WorldMonitor._lock", threading.Lock())
        self._members: List[str] = (
            list(initial_members) if initial_members is not None
            else (_default_members(world) if world is not None else []))
        self.generation = 0
        self.rank = rank if rank is not None else -1
        self.world = world if world is not None else 0
        self.joining = joining
        # False in simulated worlds: many fake ranks share one
        # process — the REAL runtime's rank/size must not be
        # rewritten; the world generation is still recorded.
        self.apply_runtime = bool(apply_runtime)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beats = 0
        self.beats_missed = 0
        # The shared failure detector owns the liveness question
        # (resilience/detector.py): this monitor registers its peers'
        # KV-lease beat ages as evidence, observer-scoped (each
        # member judges peers through its own clock/KV handle), and
        # reads graduated verdicts back — the inline lease arithmetic
        # this class used to do. One sweep thread per process,
        # however many monitors (and routers) are live.
        self._det = _detector.shared_detector()
        self._det_ns = f"wm/{next(_MONITOR_IDS)}"
        self._det_peers: set = set()
        # The never-beaten startup-grace reference (_beat_age);
        # re-anchored by start().
        self._start_t = self.clock()

    # -- heartbeats ----------------------------------------------------

    def heartbeat(self) -> bool:
        """One beat; False when the write was dropped (chaos
        ``heartbeat_drop`` or a transport fault) — the lease is sized
        to survive isolated misses (default cadence = lease/4), and a
        KV transport failure is a typed, COUNTED miss, not a raw
        socket error out of the heartbeat thread."""
        if chaos.fires("heartbeat_drop"):
            return self._miss_beat()
        try:
            self.kv.put(f"hb/{self.member_id}", {"t": self.clock()})
        except KVTransportError:
            return self._miss_beat()
        with self._lock:
            self.beats += 1
        return True

    def _miss_beat(self) -> bool:
        from horovod_tpu.obs import catalog as _obs_catalog
        _obs_catalog.elastic_metrics()["heartbeats_missed"].inc()
        with self._lock:
            self.beats_missed += 1
        return False

    def announce_join(self) -> None:
        """Publish this (non-member) process's intent to join; the
        incumbent members' watchers pick it up and propose a grow.
        Written both as a ``join/<member>`` key (scan-capable
        transports) and onto the well-known ``join_queue`` list (the
        scan-less bootstrap KV plane)."""
        self.kv.put(f"join/{self.member_id}", {"t": self.clock()})
        queue = self.kv.get("join_queue") or []
        if self.member_id not in queue:
            self.kv.put("join_queue", list(queue) + [self.member_id])
        self.heartbeat()

    def _beat_age(self, member: str, now: float) -> float:
        hb = self.kv.get(f"hb/{member}")
        if not hb:
            # Startup grace: a member that has never beaten is aged
            # from when THIS observer started watching, not from -inf
            # — real multi-process worlds stagger their starts
            # (import time, scheduler jitter), and an observer that
            # came up first must not resize a still-booting peer out
            # instantly. A peer that never comes up still expires on
            # the ordinary lease schedule.
            return now - self._start_t
        return now - float(hb.get("t", float("-inf")))

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    # -- detector plumbing --------------------------------------------

    def _peer_key(self, member: str) -> str:
        return f"{self._det_ns}/{member}"

    def _sync_detector_peers(self) -> None:
        """Register every current peer (members minus self) with the
        shared detector, KV-lease beat age as evidence; drop peers no
        longer in the world. Idempotent — called at start() and after
        every adopted commit."""
        members = self.members()
        want = {m for m in members if m != self.member_id}
        with self._lock:
            have = set(self._det_peers)
            self._det_peers = set(want)
        for m in have - want:
            self._det.unregister(self._peer_key(m))
        for m in want:
            # Re-registering refreshes rank attribution after a
            # resize (ranks are slots; stall reports name ranks).
            self._det.register(
                self._peer_key(m),
                age_fn=(lambda m=m: self._beat_age(m, self.clock())),
                clock=self.clock,
                suspect_after=self.lease_s * SUSPECT_LEASE_FRACTION,
                dead_after=self.lease_s,
                label=m, poll_s=self.heartbeat_s,
                rank=members.index(m))

    def _peer_state(self, member: str) -> str:
        """This peer's graduated verdict, evidence evaluated NOW (the
        protocol's deterministic read). Falls back to direct lease
        arithmetic for a peer not (or no longer) registered — e.g. a
        stopped monitor probing one last time."""
        key = self._peer_key(member)
        with self._lock:
            registered = member in self._det_peers
        if registered:
            return self._det.state_of(key, refresh=True)
        age = self._beat_age(member, self.clock())
        if age > self.lease_s:
            return _detector.DEAD
        if age > self.lease_s * SUSPECT_LEASE_FRACTION:
            return _detector.SUSPECT
        return _detector.ALIVE

    def alive_members(self, now: Optional[float] = None) -> List[str]:
        """Current members the detector does not call DEAD (self
        always — a member never declares itself dead; SUSPECT peers
        are still alive: drained, not removed). An explicit ``now``
        keeps the pre-detector point-in-time semantics: raw lease
        arithmetic evaluated at that timestamp (``self.clock``
        domain), bypassing the detector's graduated state."""
        dead = set(self.dead_members(now))
        return [m for m in self.members() if m not in dead]

    def dead_members(self, now: Optional[float] = None) -> List[str]:
        if now is not None:
            return [m for m in self.members()
                    if m != self.member_id
                    and self._beat_age(m, now) > self.lease_s]
        return [m for m in self.members()
                if m != self.member_id
                and self._peer_state(m) == _detector.DEAD]

    def suspect_members(self) -> List[str]:
        """Peers under graduated suspicion (stale-but-not-dead
        evidence, stall reports, flap damping) — drain candidates,
        never resize triggers."""
        return [m for m in self.members()
                if m != self.member_id
                and self._peer_state(m) == _detector.SUSPECT]

    def joiners(self) -> List[str]:
        cur = set(self.members())
        try:
            announced = [m.split("/", 1)[1]
                         for m, v in self.kv.scan("join/").items()
                         if v is not None]
        except NotImplementedError:
            # Scan-less transport (BootstrapKV): the join_queue list
            # is the announcement channel.
            announced = list(self.kv.get("join_queue") or [])
        # A joiner must also be ALIVE: a candidate that announced and
        # died before admission would stall every ack barrier it is
        # proposed into for a full lease.
        now = self.clock()
        return sorted(m for m in set(announced)
                      if m not in cur
                      and self._beat_age(m, now) <= self.lease_s)

    def pending_change(self) -> Optional[Dict]:
        """{'dead': [...], 'joiners': [...]} when the committed world
        no longer matches reality, else None. Also flags a NEWER
        COMMIT this member has not adopted (``'commit': gen``) — how
        a write-partitioned member finds out the world moved on
        without it: its own beats stopped landing, the survivors
        resized, and the only honest next step is `resize()`, which
        adopts the commit and raises `MembershipError` if it excludes
        this member (never split-brain at the old generation)."""
        dead, joiners = self.dead_members(), self.joiners()
        # Snapshot under the lock `_adopt` writes it under (hvdlint
        # HVD008): this runs on the watcher thread while a caller
        # thread may be mid-resize.
        with self._lock:
            gen = self.generation
        newer = self.kv.get(f"commit/{gen + 1}")
        if not dead and not joiners and newer is None:
            return None
        out: Dict[str, Any] = {"dead": dead, "joiners": joiners}
        if newer is not None:
            out["commit"] = gen + 1
        return out

    # -- the watcher thread --------------------------------------------

    def start(self) -> "WorldMonitor":
        """Start heartbeating + watching. Founding members also race
        to write the genesis commit (first wins; content identical)."""
        if not self.joining:
            members = self.members()
            self.kv.put_if_absent("commit/0", {
                "generation": 0, "members": list(members),
                "died": [], "joined": []})
        # hvd: disable=HVD008(written before Thread.start() below — start() publishes it to the watcher thread, happens-before, not a race)
        self._start_t = self.clock()
        self.heartbeat()
        self._sync_detector_peers()
        self._stop.clear()
        t = threading.Thread(target=self._watch_loop,
                             name=f"hvd-member-{self.member_id}",
                             daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def _watch_loop(self):
        """Heartbeat writer + change watcher. NOT a liveness sweep —
        detection belongs to the shared `FailureDetector`; this
        thread only writes this member's own beats and reacts to what
        the detector (and the commit log) already concluded. A KV
        transport fault costs the tick, never the thread."""
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.heartbeat()
                if (self.on_change is not None
                        and self.pending_change()):
                    self.on_change()
            except KVTransportError:
                continue   # typed + already counted; next tick retries

    def stop(self) -> None:
        """Stop beating and watching (clean shutdown: the lease will
        lapse and the survivors will resize us out — that is the
        protocol's ONLY removal path, so a crash and a clean exit
        look identical to the world)."""
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            self._det_peers = set()
        self._det.unregister_prefix(self._det_ns + "/")

    def die(self) -> None:
        """Abrupt death for drills: stop heartbeating NOW, no
        goodbyes (what `rank_death` simulates)."""
        self.stop()

    # -- the resize protocol -------------------------------------------

    def _adopt(self, commit: Dict) -> ResizeDecision:
        members = list(commit["members"])
        if self.member_id not in members:
            raise MembershipError(
                f"{self.member_id}: generation "
                f"{commit['generation']} excludes this member "
                f"(declared dead at {commit.get('died')}) — stop and "
                f"re-join as a new member")
        with self._lock:
            prev = list(self._members)
            self.generation = int(commit["generation"])
            self._members = members
            self.rank = members.index(self.member_id)
            self.world = len(members)
            self.joining = False
        dec = ResizeDecision(
            generation=int(commit["generation"]), world=len(members),
            rank=members.index(self.member_id), members=members,
            died=[m for m in prev if m not in members],
            joined=[m for m in members if m not in prev])
        self._sync_detector_peers()
        self.kv.delete(f"join/{self.member_id}")
        queue = self.kv.get("join_queue") or []
        if self.member_id in queue:
            self.kv.put("join_queue",
                        [m for m in queue if m != self.member_id])
        # Generation hint for scan-less joiners: where to start
        # probing prop/commit keys.
        self.kv.put("gen", int(commit["generation"]))
        from horovod_tpu.runtime import bootstrap as _bootstrap
        _bootstrap.apply_resize(dec.rank, dec.world, dec.generation,
                                rekey_runtime=self.apply_runtime)
        if dec.rank == 0:
            # One emitter per generation (the new leader): events,
            # counters, and the flight-recorder bundle that preserves
            # the run-up to the membership change.
            from horovod_tpu.obs import catalog as _obs_catalog
            from horovod_tpu.obs import events as _events
            from horovod_tpu.obs import flightrec as _flightrec
            m = _obs_catalog.elastic_metrics()
            m["world_size"].set(float(dec.world))
            if dec.generation > 0:
                m["resizes"].inc(kind=dec.kind)
                if dec.died:
                    m["rank_deaths"].inc(len(dec.died))
                if dec.joined:
                    m["rank_joins"].inc(len(dec.joined))
                for dm in dec.died:
                    _events.emit("membership.rank_death", member=dm,
                                 generation=dec.generation)
                for jm in dec.joined:
                    _events.emit("membership.rank_join", member=jm,
                                 generation=dec.generation)
                _events.emit(
                    "membership.resize", generation=dec.generation,
                    world=dec.world, resize_kind=dec.kind,
                    died=dec.died, joined=dec.joined)
                _flightrec.trigger(
                    "membership.resize", generation=dec.generation,
                    world=dec.world, died=dec.died, joined=dec.joined)
        return dec

    def current_decision(self) -> ResizeDecision:
        """The already-committed view (no protocol round)."""
        members = self.members()
        with self._lock:
            return ResizeDecision(
                generation=self.generation, world=self.world,
                rank=self.rank, members=members, died=[], joined=[])

    def resize(self, timeout_s: float = 30.0) -> ResizeDecision:
        """Run the agreement until the pending membership change is
        committed; every affected member calls this (survivors from
        their barrier interrupt, joiners via `wait_for_membership`).

        Deterministic: the proposal is survivors-in-old-rank-order
        with joiners appended (sorted by member id), first proposal
        per generation wins, commit requires every proposed member's
        ack. A proposed member dying mid-barrier stalls acks for one
        lease, after which the detectors re-propose at the next
        generation without it."""
        deadline = self.clock() + timeout_s
        attempt = self.generation + 1
        while True:
            if self.clock() > deadline:
                raise MembershipError(
                    f"{self.member_id}: resize did not commit within "
                    f"{timeout_s}s (generation {self.generation}, "
                    f"pending {self.pending_change()})")
            self.heartbeat()
            # Adopt the newest commit first — another member may have
            # finished the round while we were detecting. Targeted
            # probes (generation+1 .. attempt+1), not a scan, so the
            # scan-less bootstrap transport works identically.
            newest_commit = None
            for g in range(self.generation + 1, attempt + 2):
                c = self.kv.get(f"commit/{g}")
                if c is not None:
                    newest_commit = c
            if newest_commit is not None:
                dec = self._adopt(newest_commit)
                if self.pending_change() is None:
                    return dec
                attempt = self.generation + 1
                continue
            if self.pending_change() is None and not self.joining:
                return self.current_decision()   # spurious wake
            attempt = max(attempt, self.generation + 1)
            prop = self.kv.get(f"prop/{attempt}")
            if prop is None:
                pend = self.pending_change() or {"dead": [],
                                                 "joiners": []}
                alive = [m for m in self.members()
                         if m not in pend["dead"]]
                proposed = alive + sorted(pend["joiners"])
                prop = self.kv.put_if_absent(
                    f"prop/{attempt}",
                    {"members": proposed, "by": self.member_id,
                     "t": self.clock()})
            members = list(prop["members"])
            if self.member_id not in members:
                # Proposed out (our lease lapsed under someone else's
                # clock): wait for the commit to confirm, then stop.
                t0 = self.clock()
                while self.clock() - t0 < self.lease_s * 2:
                    c = self.kv.get(f"commit/{attempt}")
                    if c is not None:
                        self._adopt(c)   # raises MembershipError
                    time.sleep(self.heartbeat_s / 4)
                raise MembershipError(
                    f"{self.member_id}: proposed out of generation "
                    f"{attempt} by {prop.get('by')}")
            self.kv.put(f"ack/{attempt}/{self.member_id}", 1)
            t0 = self.clock()
            while self.clock() - t0 < self.lease_s:
                acked = {m for m in members
                         if self.kv.get(f"ack/{attempt}/{m}")
                         is not None}
                if set(members) <= acked:
                    commit = {
                        "generation": attempt, "members": members,
                        "died": [m for m in self.members()
                                 if m not in members],
                        "joined": [m for m in members
                                   if m not in self.members()]}
                    won = self.kv.put_if_absent(f"commit/{attempt}",
                                                commit)
                    dec = self._adopt(won)
                    if self.pending_change() is None:
                        return dec
                    attempt = self.generation + 1
                    break
                if self.kv.get(f"commit/{attempt}") is not None:
                    break   # someone else committed; adopt at loop top
                time.sleep(self.heartbeat_s / 4)
            else:
                # Barrier stalled a full lease: a proposed member died
                # mid-round. Supersede at the next generation with a
                # fresh alive set.
                attempt += 1

    def wait_for_membership(self, timeout_s: float = 30.0
                            ) -> ResizeDecision:
        """Joiner side: ack any proposal that includes us, adopt the
        commit that admits us. Probes generations from the committed
        ``gen`` hint (scan-free, so the bootstrap transport works)."""
        deadline = self.clock() + timeout_s
        while self.clock() < deadline:
            self.heartbeat()
            base = int(self.kv.get("gen") or 0)
            best = None
            for g in range(base, base + 16):
                prop = self.kv.get(f"prop/{g + 1}")
                if (prop is not None
                        and self.member_id in prop.get("members", ())):
                    self.kv.put(f"ack/{g + 1}/{self.member_id}", 1)
                commit = self.kv.get(f"commit/{g}")
                if (commit is not None
                        and self.member_id
                        in commit.get("members", ())):
                    best = commit
            if best is not None:
                return self._adopt(best)
            time.sleep(self.heartbeat_s / 4)
        raise MembershipError(
            f"{self.member_id}: no generation admitted this joiner "
            f"within {timeout_s}s")


# ---------------------------------------------------------------------------
# The resize-aware step barrier (in-process worlds).
# ---------------------------------------------------------------------------

class ElasticBarrier:
    """A cyclic barrier whose membership can change and whose waiters
    can be interrupted — the in-process stand-in for "the collective
    failed because a peer is gone".

    `wait` returns ``"ok"`` when every current member arrived,
    ``"resize"`` when the cycle was interrupted (a monitor detected a
    membership change — the step in flight must be discarded), or
    ``"timeout"``. `reconfigure(gen, members)` installs the new
    membership after a committed resize (idempotent per generation;
    an equal-generation call only clears a stale interrupt)."""

    def __init__(self, members: Sequence[str]):
        self._cond = threading.Condition()
        self._members = set(members)
        self._arrived: set = set()
        self._phase = 0
        self._interrupted = False
        self._config_gen = 0

    def interrupt(self) -> None:
        with self._cond:
            self._interrupted = True
            # Abort the in-flight cycle cleanly: every waiter returns
            # "resize" and NOBODY stays arrived — a stale arrival
            # surviving into the post-resize cycle would let one
            # member complete a barrier the others never re-entered.
            self._arrived = set()
            self._cond.notify_all()

    def reconfigure(self, gen: int, members: Sequence[str]) -> None:
        with self._cond:
            if gen < self._config_gen:
                return
            if gen > self._config_gen:
                self._config_gen = gen
                self._members = set(members)
                self._arrived = set()
                self._phase += 1
            self._interrupted = False
            self._cond.notify_all()

    def members(self) -> List[str]:
        with self._cond:
            return sorted(self._members)

    def wait(self, member: str, timeout: float = 30.0) -> str:
        deadline = time.monotonic() + timeout
        with self._cond:
            if self._interrupted:
                return "resize"
            if member not in self._members:
                return "resize"   # reconfigured out while computing
            self._arrived.add(member)
            if self._members <= self._arrived:
                self._arrived = set()
                self._phase += 1
                self._cond.notify_all()
                return "ok"
            phase = self._phase
            while self._phase == phase and not self._interrupted:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._arrived.discard(member)
                    return "timeout"
                self._cond.wait(min(remaining, 0.05))
            if self._phase != phase:
                return "ok"
            return "resize"


# ---------------------------------------------------------------------------
# The simulated elastic training world.
# ---------------------------------------------------------------------------

def record_keys(batch: Dict[str, np.ndarray]) -> List[str]:
    """Per-record content hashes of one batch — the union-stream
    currency (field names, dtypes, and raw bytes participate, so
    "bitwise identical" means exactly that; batch GROUPING does not,
    which is the point: a resize regroups records, never alters
    them)."""
    names = sorted(batch)
    n = len(batch[names[0]])
    out = []
    for i in range(n):
        h = hashlib.sha256()
        for name in names:
            a = np.ascontiguousarray(batch[name][i])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        out.append(h.hexdigest())
    return out


@dataclasses.dataclass
class WorldRunReport:
    """What one simulated elastic run did (the union proof's chaos
    leg, the CI smoke's assertion surface, bench's artifact)."""

    completed: bool
    final_world: int
    final_generation: int
    steps: int
    epochs: int
    deaths: List[str]
    joins: List[str]
    resizes: List[Dict]          # per commit: gen/world/kind/timings
    logs: Dict[str, List]        # member -> [(step, [record keys])]
    final_state: Optional[Dict] = None
    error: Optional[str] = None

    def union_keys(self) -> List[str]:
        """Every record key whose training survived into the final
        state (each log already trimmed to its member's last committed
        step at every resize) — sorted, as a multiset."""
        out: List[str] = []
        for entries in self.logs.values():
            for _step, keys in entries:
                out.extend(keys)
        return sorted(out)

    def summary(self) -> Dict:
        detect = sorted(r["detect_s"] for r in self.resizes
                        if r.get("detect_s") is not None)
        resume = sorted(r["resume_s"] for r in self.resizes
                        if r.get("resume_s") is not None)
        return {
            "completed": self.completed,
            "final_world": self.final_world,
            "final_generation": self.final_generation,
            "steps": self.steps,
            "deaths": len(self.deaths),
            "joins": len(self.joins),
            "resizes": len(self.resizes),
            "records_reassigned": sum(
                r.get("records_reassigned", 0) for r in self.resizes),
            "detect_s": {
                "p50": round(detect[len(detect) // 2], 3)
                if detect else None,
                "max": round(detect[-1], 3) if detect else None},
            "time_to_resume_s": {
                "p50": round(resume[len(resume) // 2], 3)
                if resume else None,
                "max": round(resume[-1], 3) if resume else None},
            "error": self.error,
        }


class SimulatedWorld:
    """An N-member in-process elastic training world (CPU test double
    for a multi-host fleet): each member is a thread with its own
    `WorldMonitor`, `ShardedDataset` shard view, and `ElasticTrainer`
    over ONE shared checkpoint directory; steps run in lockstep
    through an `ElasticBarrier` with gradients averaged across the
    contributing members (deterministic rank-order float64 sum).

    Chaos opportunities (all leader-offered at step boundaries so a
    one-shot ``HVD_CHAOS=rank_death:1`` arming is deterministic):
    ``rank_death`` — once a checkpoint is committed, the
    highest-ranked member stops heartbeating and its thread dies;
    ``rank_join`` — while the world is below its launch size, a new
    member announces itself and is admitted by a grow resize.

    The loop only checkpoints on FULL lockstep steps (every live
    member contributed a batch), so the snapshot's single cursor
    describes every rank — the invariant `data.remainder_after`'s
    consumed-set math stands on.
    """

    def __init__(self, *, world: int, make_dataset: Callable,
                 state0: Dict, grad_fn: Callable, apply_fn: Callable,
                 ckpt_dir: str, epochs: int, save_every: int = 2,
                 lease_s: float = 0.4,
                 heartbeat_s: Optional[float] = None,
                 join_member_prefix: str = "joiner",
                 max_joins: int = 1,
                 kv: Optional[Any] = None):
        self.world0 = int(world)
        self.make_dataset = make_dataset
        self.state0 = state0
        self.grad_fn = grad_fn
        self.apply_fn = apply_fn
        self.ckpt_dir = ckpt_dir
        self.epochs = int(epochs)
        self.save_every = int(save_every)
        self.lease_s = float(lease_s)
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s
                            else self.lease_s / 4.0)
        self.join_member_prefix = join_member_prefix
        self.max_joins = int(max_joins)
        self.kv = kv if kv is not None else InProcessKV()
        self.members0 = _default_members(world)
        self.barrier = ElasticBarrier(self.members0)
        self._lock = lockcheck.register(
            "SimulatedWorld._lock", threading.Lock())
        self._ctl: Dict[str, Any] = {
            "victim": None, "stop": False, "joins_spawned": 0,
            "contrib": {}, "death_t": {}, "logs": {}, "resizes": [],
            "deaths": [], "joins": [], "final": {}, "errors": [],
        }
        self._threads: List[threading.Thread] = []

    # -- shared-control helpers (all under self._lock) -----------------

    def _log_keys(self, member: str, step: int, keys: List[str]):
        with self._lock:
            self._ctl["logs"].setdefault(member, []).append(
                (int(step), list(keys)))

    def _trim_log(self, member: str, step: int):
        """Drop a member's record log past `step` — those batches'
        effects died with the rollback."""
        with self._lock:
            log = self._ctl["logs"].get(member, [])
            self._ctl["logs"][member] = [
                ent for ent in log if ent[0] <= step]

    # -- member threads ------------------------------------------------

    def _spawn(self, member: str, rank: Optional[int],
               joining: bool):
        t = threading.Thread(
            target=self._member_main, args=(member, rank, joining),
            name=f"hvd-sim-{member}", daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()

    def _member_main(self, member: str, rank: Optional[int],
                     joining: bool):
        try:
            self._member_loop(member, rank, joining)
        except MembershipError:
            return   # declared dead mid-protocol: the drill's point
        # hvd: disable=HVD006(simulated-world member: any unexpected fault must surface in the report, not hang the join)
        except Exception as e:
            with self._lock:
                self._ctl["errors"].append(f"{member}: {e!r}")
            self.barrier.interrupt()

    def _build_trainer(self, dec_rank: int, dec_world: int):
        from horovod_tpu.resilience.elastic import ElasticTrainer
        ds = self.make_dataset(dec_rank, dec_world)
        trainer = ElasticTrainer(
            self.ckpt_dir,
            save_every=self.save_every if dec_rank == 0 else 0,
            keep=0, block=True, install_signals=False,
            dataset=ds, migrate_world=True)
        state, step = trainer.resume(like=self.state0)
        return ds, trainer, state, step

    def _offer_chaos(self, monitor: WorldMonitor, trainer) -> None:
        """Leader-only, step-boundary chaos opportunities (see class
        docstring for why they are leader-offered)."""
        if monitor.rank != 0:
            return
        committed = getattr(trainer, "_last_good_step", None)
        if committed and committed >= self.save_every:
            if chaos.fires("rank_death"):
                victims = [m for m in monitor.members()
                           if m != monitor.member_id]
                if victims:
                    victim = victims[-1]   # highest current rank
                    with self._lock:
                        self._ctl["victim"] = victim
                        self._ctl["deaths"].append(victim)
        if monitor.world < self.world0:
            with self._lock:
                spawned = self._ctl["joins_spawned"]
            if spawned < self.max_joins and chaos.fires("rank_join"):
                with self._lock:
                    self._ctl["joins_spawned"] = spawned + 1
                    jid = f"{self.join_member_prefix}{spawned}"
                    self._ctl["joins"].append(jid)
                self._spawn(jid, None, True)

    def _resize(self, member: str, monitor: WorldMonitor):
        """Survivor side of a detected change: agree, reconfigure the
        barrier, roll back to the committed snapshot, rebalance.

        Returns ``None`` for a spurious wake (a stale interrupt after
        the generation already committed, or a barrier timeout with
        nothing actually pending): the caller keeps its state and its
        in-flight contribution — rolling back on a phantom resize
        would discard legitimately-trained steps and inflate the
        resize accounting."""
        gen_before = monitor.generation
        t_detect = time.monotonic()
        dec = monitor.resize(timeout_s=max(10.0, self.lease_s * 40))
        self.barrier.reconfigure(dec.generation, dec.members)
        if dec.generation == gen_before:
            return None
        ds, trainer, state, step = self._build_trainer(
            dec.rank, dec.world)
        self._trim_log(member, step)
        t_done = time.monotonic()
        if dec.rank == 0:
            with self._lock:
                recorded = {r["generation"]
                            for r in self._ctl["resizes"]}
                if dec.generation not in recorded:
                    for dm in dec.died:
                        # The dead member's post-commit batches died
                        # with it — trim its log to the step we
                        # rolled back to.
                        log = self._ctl["logs"].get(dm, [])
                        self._ctl["logs"][dm] = [
                            ent for ent in log if ent[0] <= step]
                    death_t = [self._ctl["death_t"].get(dm)
                               for dm in dec.died]
                    death_t = [t for t in death_t if t is not None]
                    self._ctl["resizes"].append({
                        "generation": dec.generation,
                        "world": dec.world,
                        "kind": dec.kind, "died": dec.died,
                        "joined": dec.joined, "committed_step": step,
                        "detect_s": round(
                            t_done - max(death_t), 3)
                        if death_t else None,
                        "resume_s": round(t_done - t_detect, 3),
                        "records_reassigned": int(
                            (ds.last_rebalance or {}).get(
                                "records_reassigned", 0)),
                    })
        return dec, ds, trainer, state, step

    def _member_loop(self, member: str, rank: Optional[int],
                     joining: bool):
        monitor = WorldMonitor(
            member, rank=rank, world=None if joining else self.world0,
            kv=self.kv, initial_members=None if joining
            else self.members0, lease_s=self.lease_s,
            heartbeat_s=self.heartbeat_s,
            on_change=self.barrier.interrupt, joining=joining,
            apply_runtime=False)
        ds = trainer = None
        try:
            if joining:
                monitor.announce_join()
                monitor.start()
                dec = monitor.wait_for_membership(
                    timeout_s=max(10.0, self.lease_s * 40))
                self.barrier.reconfigure(dec.generation, dec.members)
            else:
                monitor.start()
            ds, trainer, state, step = self._build_trainer(
                monitor.rank, monitor.world)
            self._trim_log(member, step)
            e0, b0 = trainer.data_start
            epoch = e0
            it = iter(ds.epoch(epoch, start_batch=b0))
            # The contribution drawn for the CURRENT step. Kept across
            # spurious barrier interrupts (the iterator cannot un-draw
            # a batch — on a phantom resize the same contribution is
            # simply re-posted; a REAL resize rebuilds the iterator
            # from the rolled-back cursor and discards it).
            pending = None
            while True:
                with self._lock:
                    if self._ctl["stop"]:
                        return
                status = self.barrier.wait(member)
                if status != "ok":
                    out = self._resize(member, monitor)
                    if out is not None:
                        dec, ds2, trainer, state, step = out
                        if ds is not None and ds is not ds2:
                            ds.close()
                        ds = ds2
                        e0, b0 = trainer.data_start
                        epoch = e0
                        it = iter(ds.epoch(epoch, start_batch=b0))
                        pending = None
                    continue
                self._offer_chaos(monitor, trainer)
                with self._lock:
                    victim = self._ctl["victim"]
                if victim == member:
                    with self._lock:
                        self._ctl["death_t"][member] = time.monotonic()
                        self._ctl["victim"] = None
                    monitor.die()
                    return
                if pending is None:
                    batch = next(it, None)
                    if batch is not None:
                        grads, loss = self.grad_fn(state, batch)
                        pending = {"grads": grads, "loss": loss,
                                   "keys": record_keys(batch)}
                    else:
                        pending = {"grads": None, "loss": None,
                                   "keys": []}
                with self._lock:
                    self._ctl["contrib"][member] = dict(
                        pending, epoch=epoch, step=step)
                status = self.barrier.wait(member)
                if status != "ok":
                    # Step in flight when the membership changed: no
                    # one applied it — resize (a REAL one discards
                    # it; a phantom one re-posts `pending`).
                    out = self._resize(member, monitor)
                    if out is not None:
                        dec, ds2, trainer, state, step = out
                        if ds is not None and ds is not ds2:
                            ds.close()
                        ds = ds2
                        e0, b0 = trainer.data_start
                        epoch = e0
                        it = iter(ds.epoch(epoch, start_batch=b0))
                        pending = None
                    continue
                live = set(monitor.members())
                with self._lock:
                    contribs = {
                        m: c for m, c in self._ctl["contrib"].items()
                        if m in live and c["epoch"] == epoch
                        and c["step"] == step}
                order = [m for m in monitor.members()
                         if m in contribs
                         and contribs[m]["grads"] is not None]
                if not order:
                    # Every live member exhausted the epoch.
                    epoch += 1
                    pending = None
                    if epoch >= self.epochs:
                        with self._lock:
                            self._ctl["final"][member] = {
                                "state": state, "step": step,
                                "world": monitor.world,
                                "generation": monitor.generation}
                        return
                    it = iter(ds.epoch(epoch))
                    continue
                avg = {
                    k: sum(np.asarray(contribs[m]["grads"][k],
                                      dtype=np.float64)
                           for m in order) / len(order)
                    for k in contribs[order[0]]["grads"]}
                loss_mean = float(
                    sum(float(contribs[m]["loss"]) for m in order)
                    / len(order))
                state = self.apply_fn(state, avg)
                step += 1
                if pending["keys"]:
                    self._log_keys(member, step, pending["keys"])
                pending = None
                full = len(order) == len(live)
                if monitor.rank == 0 and full:
                    state = trainer.after_step(step, state, loss_mean)
        finally:
            monitor.stop()
            if ds is not None:
                ds.close()

    # -- the driver ----------------------------------------------------

    def run(self, timeout_s: float = 120.0) -> WorldRunReport:
        for i, member in enumerate(self.members0):
            self._spawn(member, i, False)
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                threads = list(self._threads)
            if all(not t.is_alive() for t in threads):
                break
            if time.monotonic() > deadline:
                with self._lock:
                    self._ctl["stop"] = True
                    self._ctl["errors"].append(
                        f"run did not finish within {timeout_s}s")
                self.barrier.interrupt()
                for t in threads:
                    t.join(timeout=5.0)
                break
            time.sleep(0.02)
        with self._lock:
            ctl = self._ctl
            finals = dict(ctl["final"])
            errors = list(ctl["errors"])
            completed = (not errors and len(finals) > 0)
            worlds = {f["world"] for f in finals.values()}
            gens = {f["generation"] for f in finals.values()}
            steps = {f["step"] for f in finals.values()}
            if completed and (len(worlds) != 1 or len(gens) != 1
                              or len(steps) != 1):
                errors.append(
                    f"finishers disagree: worlds={worlds} gens={gens} "
                    f"steps={steps}")
                completed = False
            any_final = next(iter(finals.values()), None)
            return WorldRunReport(
                completed=completed,
                final_world=any_final["world"] if any_final else 0,
                final_generation=(any_final["generation"]
                                  if any_final else 0),
                steps=any_final["step"] if any_final else 0,
                epochs=self.epochs,
                deaths=list(ctl["deaths"]),
                joins=list(ctl["joins"]),
                resizes=list(ctl["resizes"]),
                logs={m: list(v) for m, v in ctl["logs"].items()},
                final_state=(any_final or {}).get("state"),
                error="; ".join(errors) if errors else None,
            )
