"""The multi-controller elastic drill — real processes, real SIGKILL.

`SimulatedWorld` (membership.py) proves the elastic-membership
machinery inside ONE process: fake ranks as threads, `InProcessKV` as
the transport, `die()` as death. This module graduates every one of
those stand-ins:

* **real worker processes** launched by ``hvdrun --elastic`` (the
  launcher's new elastic mode: a worker death does not kill the job);
* **the real rendezvous KV server** as the transport —
  `bootstrap.connect_kv()` attaches each worker to the launcher's
  native KV plane WITHOUT full `init()` (no jax backend, no init
  barrier), and ``membership.install_kv(BootstrapKV(...))`` makes it
  the membership transport, retry-hardened with typed errors;
* **a real ``SIGKILL``** of one worker mid-epoch — no atexit, no
  goodbye beat, the process is simply gone;
* survivors detect the lapsed lease through the shared
  `FailureDetector`, run the propose/ack/commit resize,
  `bootstrap.apply_resize` re-keys the runtime, `ElasticTrainer`
  rolls back to the committed `TrainSnapshot` and rebalances shards —
  **exact resume**, proven by the same union contract as the
  simulated harness: the multiset union of all members' effective
  per-record streams equals every dataset record exactly once per
  epoch.

Workers coordinate lockstep training THROUGH THE KV ONLY — each
member publishes its gradient contribution under
``c/<generation>/<epoch>/<step>/<member>`` and folds the full set
deterministically (rank-order float64 average) — no cross-process jax
collectives, so the drill runs on any box, including one whose CPU
jaxlib cannot back `jax.distributed` collectives (unlike the
known-env runner tests).

CI entry (ci.sh ``elastic-mc`` smoke; docs/resilience.md)::

    python -m horovod_tpu.resilience.drill --workdir /tmp/mc \\
        --world 3 --kill-rank 2

`bench.py --elastic-check --real-procs` records the same report —
detect_s and time_to_resume_s for the real multi-process path — as a
benchmark artifact.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

import numpy as np

DEFAULT_WORLD = 3
DEFAULT_EPOCHS = 2
DEFAULT_RECORDS = 48
DEFAULT_BATCH = 4
DEFAULT_SAVE_EVERY = 2
# Default SIGKILL point: after the first committed snapshot (step 2
# at save_every=2) but strictly MID-epoch (a 3-rank 48-record world
# runs 4 lockstep steps per epoch), so the rollback leaves a nonempty
# untrained remainder to rebalance.
DEFAULT_KILL_STEP = 3
# Roomy on purpose: the drill shares its box with whatever else runs
# (a loaded CI machine staggers worker starts and steals whole GIL
# quanta); detection latency ~= the lease, and 2 s is still a crisp
# headline number for a real SIGKILL.
DEFAULT_LEASE_S = 2.0

_POLL_S = 0.01


def _say(msg: str) -> None:
    print(msg, flush=True)


# ---------------------------------------------------------------------------
# Shared workload (the equivalence harness's pure-numpy SGD).
# ---------------------------------------------------------------------------

def _grad(state, batch):
    x = batch["x"].astype(np.float64)
    y = batch["y"].astype(np.float64)
    err = x @ state["w"] + state["b"] - y
    return ({"w": (x.T @ err / len(y)).tolist(),
             "b": float(err.mean())},
            float((err ** 2).mean()))


def _apply(state, grads, lr: float = 0.05):
    return {"w": state["w"] - lr * np.asarray(grads["w"], np.float64),
            "b": state["b"] - lr * np.float64(grads["b"])}


def _state0(dim: int) -> Dict:
    return {"w": np.zeros(dim, np.float64), "b": np.float64(0.0)}


def _digest(state) -> str:
    import hashlib
    h = hashlib.sha256()
    for k in sorted(state):
        a = np.ascontiguousarray(np.asarray(state[k], np.float64))
        h.update(k.encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _manifest_path(workdir: str) -> str:
    return os.path.join(workdir, "manifest.json")


def _load_manifest(workdir: str) -> Dict:
    with open(_manifest_path(workdir)) as f:
        return json.load(f)


def _make_ds(manifest: Dict, rank: int, world: int):
    from horovod_tpu import data as hd
    spec = [tuple([n, d, tuple(s)]) for n, d, s in manifest["spec"]]
    return hd.ShardedDataset(
        manifest["paths"], spec, manifest["batch"], shuffle=True,
        seed=manifest["seed"], rank=rank, world=world)


# ---------------------------------------------------------------------------
# The worker (one per hvdrun-launched process).
# ---------------------------------------------------------------------------

def _append_jsonl(path: str, obj) -> None:
    # O_APPEND single-write lines + flush: a SIGKILL loses at most the
    # user-space buffer of the CURRENT line, never a committed one.
    with open(path, "a") as f:
        f.write(json.dumps(obj) + "\n")
        f.flush()


def _read_jsonl(path: str) -> List:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue   # torn tail line (SIGKILL mid-write)
    except OSError:
        pass
    return out


def _truncate_log(path: str, step: int) -> None:
    """Drop a member's record-log entries past ``step`` — those
    batches' effects died with the rollback (the SimulatedWorld trim,
    durable across processes)."""
    entries = [e for e in _read_jsonl(path) if e["step"] <= step]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    os.replace(tmp, path)


class _Worker:
    """One member's lifetime inside the drill world."""

    def __init__(self, args):
        from horovod_tpu.runtime import config as _config
        self.a = args
        self.rank0 = int(_config.env_raw("HOROVOD_RANK") or 0)
        self.world0 = int(_config.env_raw("HOROVOD_SIZE") or 1)
        self.member = f"rank{self.rank0}"
        self.workdir = args.workdir
        self.manifest = _load_manifest(self.workdir)
        self.log_path = os.path.join(self.workdir, "logs",
                                     f"{self.member}.jsonl")
        self.ds = None
        self.trainer = None

    # -- world plumbing -----------------------------------------------

    def _build(self, rank: int, world: int):
        from horovod_tpu.resilience.elastic import ElasticTrainer
        if self.ds is not None:
            self.ds.close()
        self.ds = _make_ds(self.manifest, rank, world)
        self.trainer = ElasticTrainer(
            os.path.join(self.workdir, "ckpt"),
            save_every=self.a.save_every if rank == 0 else 0,
            keep=0, block=True, install_signals=False,
            dataset=self.ds, migrate_world=True)
        state, step = self.trainer.resume(
            like=_state0(self.manifest["dim"]))
        _truncate_log(self.log_path, step)
        return state, step

    def _committed_step(self) -> int:
        """Newest COMMITTED step in the shared checkpoint dir (the
        leader writes it; the victim only reads the directory — its
        own trainer never saves)."""
        ckpt_dir = os.path.join(self.workdir, "ckpt")
        best = 0
        try:
            names = os.listdir(ckpt_dir)
        except OSError:
            return 0
        for n in names:
            if (n.startswith("step_") and n[5:].isdigit()
                    and os.path.isfile(os.path.join(
                        ckpt_dir, n, "_CHECKPOINT_METADATA"))):
                best = max(best, int(n[5:]))
        return best

    def _maybe_die(self, step: int) -> None:
        """The drill's fault: a REAL SIGKILL of this process at the
        scheduled step, once a snapshot is committed (so there is
        something exact to resume from). No cleanup, no last beat —
        the lease must find out the hard way."""
        if self.a.kill_rank is None or self.rank0 != self.a.kill_rank:
            return
        committed = self._committed_step()
        if step >= self.a.kill_step and committed >= self.a.save_every:
            _append_jsonl(
                os.path.join(self.workdir, "deaths.jsonl"),
                {"member": self.member, "step": step,
                 "t": time.time()})
            _say(f"drill worker {self.member}: SIGKILL at step "
                 f"{step} (committed {committed})")
            os.kill(os.getpid(), signal.SIGKILL)

    def _wait_for_world(self, kv, monitor,
                        timeout_s: float = 120.0) -> bool:
        """Hold at the start line until every launch member has
        beaten at least once (worker starts stagger — imports,
        scheduler jitter): nobody consults liveness before the world
        actually assembled. Past the timeout the lease semantics
        take over (a member that never came up IS dead)."""
        from horovod_tpu.resilience.membership import KVTransportError
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                missing = [m for m in monitor.members()
                           if kv.get(f"hb/{m}") is None]
            except KVTransportError:
                missing = ["<kv unreachable>"]
            if not missing:
                return True
            time.sleep(0.05)
        _say(f"drill worker {self.member}: world incomplete after "
             f"{timeout_s}s ({missing}); proceeding on lease "
             f"semantics")
        return False

    def _gather(self, kv, monitor, gen: int, epoch: int, step: int
                ) -> Optional[Dict]:
        """Wait for every member's contribution at (gen, epoch, step)
        — the KV-coordinated step barrier. Returns None when the
        membership changed underneath (caller resizes)."""
        from horovod_tpu.resilience.membership import KVTransportError
        while True:
            members = monitor.members()
            vals = {}
            complete = True
            for m in members:
                try:
                    v = kv.get(f"c/{gen}/{epoch}/{step}/{m}")
                except KVTransportError:
                    v = None
                if v is None:
                    complete = False
                    break
                vals[m] = v
            if complete:
                return vals
            try:
                if monitor.pending_change() is not None:
                    return None
            except KVTransportError:
                pass
            time.sleep(_POLL_S)

    def _resize(self, monitor, gen_before: int, t_detect: float):
        """Survivor side: agree, roll back, rebalance. Returns the
        resumed (state, step, gen) or None on a spurious wake."""
        dec = monitor.resize(
            timeout_s=max(20.0, self.a.lease_s * 40))
        if dec.generation == gen_before:
            return None
        t_commit = time.time()   # agreed — BEFORE rollback/rebalance
        state, step = self._build(dec.rank, dec.world)
        t_done = time.time()
        if dec.rank == 0:
            _append_jsonl(
                os.path.join(self.workdir, "resizes.jsonl"),
                {"generation": dec.generation, "world": dec.world,
                 "kind": dec.kind, "died": dec.died,
                 "joined": dec.joined, "committed_step": step,
                 "t_detect": t_detect, "t_commit": t_commit,
                 "resume_s": round(t_done - t_detect, 3),
                 "records_reassigned": int(
                     (self.ds.last_rebalance or {}).get(
                         "records_reassigned", 0))})
        _say(f"drill worker {self.member}: adopted generation "
             f"{dec.generation} world={dec.world} rank={dec.rank} "
             f"(rolled back to step {step})")
        return state, step, dec.generation

    # -- the lockstep loop --------------------------------------------

    def run(self) -> int:
        from horovod_tpu.resilience.membership import (
            BootstrapKV, KVTransportError, MembershipError,
            WorldMonitor, install_kv, record_keys)
        from horovod_tpu.runtime import bootstrap

        native = bootstrap.connect_kv()
        kv = BootstrapKV(native)
        install_kv(kv)
        monitor = WorldMonitor(
            self.member, rank=self.rank0, world=self.world0, kv=kv,
            lease_s=self.a.lease_s,
            heartbeat_s=self.a.lease_s / 4.0)
        monitor.start()
        _say(f"drill worker {self.member}: joined world "
             f"{self.world0} via rendezvous KV")
        self._wait_for_world(kv, monitor)
        try:
            state, step = self._build(monitor.rank, monitor.world)
            epoch, b0 = self.trainer.data_start
            it = iter(self.ds.epoch(epoch, start_batch=b0))
            gen = monitor.generation
            pending = None
            while True:
                try:
                    pend = monitor.pending_change()
                except KVTransportError:
                    pend = None
                if pend is not None:
                    out = self._resize(monitor, gen, time.time())
                    if out is not None:
                        state, step, gen = out
                        epoch, b0 = self.trainer.data_start
                        it = iter(self.ds.epoch(epoch,
                                                start_batch=b0))
                        pending = None
                    continue
                self._maybe_die(step)
                if pending is None:
                    batch = next(it, None)
                    if batch is None:
                        pending = {"grads": None, "loss": None,
                                   "keys": []}
                    else:
                        grads, loss = _grad(state, batch)
                        pending = {"grads": grads, "loss": loss,
                                   "keys": record_keys(batch)}
                try:
                    kv.put(f"c/{gen}/{epoch}/{step}/{self.member}",
                           {"grads": pending["grads"],
                            "loss": pending["loss"]})
                except KVTransportError:
                    time.sleep(_POLL_S)
                    continue   # retry the publish next round
                contribs = self._gather(kv, monitor, gen, epoch, step)
                if contribs is None:
                    continue   # membership changed: resize at loop top
                members = monitor.members()
                order = [m for m in members
                         if contribs[m]["grads"] is not None]
                if not order:
                    # Every live member exhausted the epoch.
                    epoch += 1
                    pending = None
                    if epoch >= self.manifest["epochs"]:
                        break
                    it = iter(self.ds.epoch(epoch))
                    continue
                avg = {k: sum(np.asarray(contribs[m]["grads"][k],
                                         np.float64)
                              for m in order) / len(order)
                       for k in contribs[order[0]]["grads"]}
                loss_mean = float(
                    sum(float(contribs[m]["loss"]) for m in order)
                    / len(order))
                state = _apply(state, avg)
                step += 1
                if pending["keys"]:
                    _append_jsonl(self.log_path,
                                  {"step": step,
                                   "keys": pending["keys"]})
                pending = None
                if monitor.rank == 0 and len(order) == len(members):
                    state = self.trainer.after_step(step, state,
                                                    loss_mean)
            final = {"member": self.member, "step": step,
                     "generation": monitor.generation,
                     "world": monitor.world,
                     "digest": _digest(state)}
            with open(os.path.join(self.workdir, "final",
                                   f"{self.member}.json"), "w") as f:
                json.dump(final, f)
            _say(f"drill worker {self.member}: DONE {final}")
            return 0
        except MembershipError as e:
            # Declared dead / partitioned out: the only safe exit.
            # Nonzero ON PURPOSE — in this drill only the SIGKILL'd
            # worker may leave the world, so a survivor landing here
            # fails the job (hvdrun --elastic tolerates signal deaths,
            # not status failures).
            _say(f"drill worker {self.member}: excluded from the "
                 f"world ({e}); exiting")
            return 3
        finally:
            monitor.stop()
            if self.ds is not None:
                self.ds.close()


# ---------------------------------------------------------------------------
# The driver.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DrillReport:
    """What one hvdrun-launched drill proved (the ci.sh assertion
    surface and the bench artifact)."""

    ok: bool
    union_match: bool
    finals_agree: bool
    launcher_rc: int
    world0: int
    final_world: int
    final_generation: int
    deaths: int
    resizes: int
    records: int
    records_reassigned: int
    detect_s: Optional[float]        # SIGKILL -> commit adopted
    time_to_resume_s: Optional[float]  # detection -> resumed
    error: Optional[str] = None

    def summary(self) -> Dict:
        return dataclasses.asdict(self)


def _write_workdir(workdir: str, *, world: int, epochs: int,
                   records: int, batch: int, dim: int, seed: int,
                   save_every: int) -> Dict:
    from horovod_tpu.resilience.equivalence import _write_dataset
    os.makedirs(os.path.join(workdir, "logs"), exist_ok=True)
    os.makedirs(os.path.join(workdir, "final"), exist_ok=True)
    paths, spec = _write_dataset(workdir, records=records, dim=dim,
                                 num_shards=world, seed=seed)
    manifest = {
        "paths": list(paths),
        "spec": [[n, d, list(s)] for n, d, s in spec],
        "batch": batch, "seed": seed, "dim": dim, "epochs": epochs,
        "records": records, "world": world, "save_every": save_every,
    }
    with open(_manifest_path(workdir), "w") as f:
        json.dump(manifest, f)
    return manifest


def _expected_union(manifest: Dict) -> List[str]:
    """The control: every dataset record exactly once per epoch —
    computed directly (record hashing ignores batch grouping, and a
    resize regroups records, never alters them)."""
    from horovod_tpu.resilience.membership import record_keys
    ds = _make_ds(manifest, 0, 1)
    keys: List[str] = []
    try:
        for batch in ds.epoch(0):
            keys.extend(record_keys(batch))
    finally:
        ds.close()
    return sorted(keys * manifest["epochs"])


def run_drill(workdir: str, *,
              world: int = DEFAULT_WORLD,
              epochs: int = DEFAULT_EPOCHS,
              records: int = DEFAULT_RECORDS,
              batch: int = DEFAULT_BATCH,
              dim: int = 3,
              seed: int = 11,
              save_every: int = DEFAULT_SAVE_EVERY,
              kill_rank: Optional[int] = None,
              kill_step: int = DEFAULT_KILL_STEP,
              lease_s: float = DEFAULT_LEASE_S,
              timeout_s: Optional[float] = None,
              log=None) -> DrillReport:
    """Launch the drill world under ``hvdrun --elastic``, SIGKILL the
    scheduled worker, and verify the survivors' exact resume: finals
    agree, >= 1 committed shrink, and the effective per-record union
    is bitwise the full dataset x epochs.

    ``kill_rank``: ``None`` picks the default victim (the highest
    rank); a NEGATIVE value disables the kill entirely (a fault-free
    baseline run — no death/resize expected, only the union check)."""
    from horovod_tpu.runtime.config import env_float
    if timeout_s is None:
        timeout_s = env_float("HVD_ELASTIC_DRILL_TIMEOUT_S", 300.0)
    if kill_rank is None:
        kill_rank = world - 1
    if kill_rank < 0:
        kill_rank = None   # fault disabled
    os.makedirs(workdir, exist_ok=True)
    manifest = _write_workdir(
        workdir, world=world, epochs=epochs, records=records,
        batch=batch, dim=dim, seed=seed, save_every=save_every)
    expected = _expected_union(manifest)

    cmd = [sys.executable, "-m", "horovod_tpu.runner",
           "-np", str(world), "--platform", "cpu", "--elastic", "--",
           sys.executable, "-m", "horovod_tpu.resilience.drill",
           "--worker", "--workdir", workdir,
           "--save-every", str(save_every),
           "--lease-s", str(lease_s),
           "--kill-rank", str(kill_rank if kill_rank is not None
                              else -1),
           "--kill-step", str(kill_step)]
    env = dict(os.environ)
    # Workers coordinate through the KV only, but the leader's
    # checkpoint saves touch jax — pin the backend to CPU so a worker
    # never stalls PROBING for an accelerator (a 30-retry TPU
    # metadata fetch holds the GIL long enough to lapse its own
    # heartbeat lease — a real finding from this drill's first run).
    env["JAX_PLATFORMS"] = "cpu"
    # The launcher/workers must resolve horovod_tpu however THIS
    # process did (repo checkout on sys.path, not installed).
    import horovod_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(horovod_tpu.__file__)))
    env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else pkg_root)
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout_s)
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = ((e.stdout or b"").decode(errors="replace")
               if isinstance(e.stdout, bytes) else (e.stdout or ""))
        out += "\n<driver: drill timed out>"
    if log is not None:
        log(out)

    deaths = _read_jsonl(os.path.join(workdir, "deaths.jsonl"))
    resizes = _read_jsonl(os.path.join(workdir, "resizes.jsonl"))
    logs: Dict[str, List] = {}
    logdir = os.path.join(workdir, "logs")
    for name in sorted(os.listdir(logdir)):
        if not name.endswith(".jsonl"):
            continue   # a .tmp staging file a crash left behind
        member = name[:-len(".jsonl")]
        logs[member] = _read_jsonl(os.path.join(logdir, name))
    # The dead member's post-commit batches died with it: trim to the
    # step the survivors rolled back to (survivors self-trim on
    # resize; the corpse cannot).
    for rz in resizes:
        for dm in rz.get("died", ()):
            logs[dm] = [e for e in logs.get(dm, ())
                        if e["step"] <= rz["committed_step"]]
    union = sorted(k for entries in logs.values()
                   for e in entries for k in e["keys"])
    finals = []
    fdir = os.path.join(workdir, "final")
    for name in sorted(os.listdir(fdir)):
        with open(os.path.join(fdir, name)) as f:
            finals.append(json.load(f))
    finals_agree = (
        len(finals) > 0
        and len({f["digest"] for f in finals}) == 1
        and len({f["step"] for f in finals}) == 1
        and len({(f["generation"], f["world"]) for f in finals}) == 1)
    union_match = union == expected
    detect_s = None
    resume_s = None
    if deaths and resizes:
        # detect_s = SIGKILL -> the recorder flagged the pending
        # change (pure lease-detection latency); the rollback +
        # rebalance that follows is time_to_resume_s, not detection.
        first = resizes[0]
        detect_s = round(first["t_detect"] - deaths[0]["t"], 3)
        resume_s = first.get("resume_s")
    errors = []
    if rc != 0:
        errors.append(f"launcher exited {rc}")
    if kill_rank is not None and not deaths:
        errors.append("the scheduled SIGKILL never happened")
    if kill_rank is not None and not resizes:
        errors.append("no resize committed")
    if not finals_agree:
        errors.append(f"finals disagree/missing: {finals}")
    if not union_match:
        errors.append(
            f"union mismatch: {len(union)} effective records vs "
            f"{len(expected)} expected")
    report = DrillReport(
        ok=not errors,
        union_match=union_match,
        finals_agree=finals_agree,
        launcher_rc=rc,
        world0=world,
        final_world=finals[0]["world"] if finals else 0,
        final_generation=finals[0]["generation"] if finals else 0,
        deaths=len(deaths),
        resizes=len(resizes),
        records=len(union),
        records_reassigned=sum(r.get("records_reassigned", 0)
                               for r in resizes),
        detect_s=detect_s,
        time_to_resume_s=resume_s,
        error="; ".join(errors) if errors else None)
    if log is not None:
        log(f"drill wall time {time.time() - t0:.1f}s")
    return report


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.resilience.drill",
        description="multi-controller elastic drill: hvdrun workers "
                    "over the rendezvous KV, real SIGKILL, "
                    "detect -> resize -> exact resume")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--worker", action="store_true",
                    help="run as ONE drill worker (internal; spawned "
                         "by the driver under hvdrun)")
    ap.add_argument("--world", type=int, default=DEFAULT_WORLD)
    ap.add_argument("--epochs", type=int, default=DEFAULT_EPOCHS)
    ap.add_argument("--records", type=int, default=DEFAULT_RECORDS)
    ap.add_argument("--batch-size", type=int, default=DEFAULT_BATCH)
    ap.add_argument("--save-every", type=int,
                    default=DEFAULT_SAVE_EVERY)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--kill-rank", type=int, default=None,
                    help="worker (launch rank) to SIGKILL mid-epoch "
                         "(default: the highest rank; negative "
                         "disables the kill)")
    ap.add_argument("--kill-step", type=int, default=DEFAULT_KILL_STEP)
    ap.add_argument("--lease-s", type=float, default=DEFAULT_LEASE_S)
    ap.add_argument("--timeout-s", type=float, default=None)
    args = ap.parse_args(argv)

    if args.worker:
        return _Worker(args).run()

    report = run_drill(
        args.workdir, world=args.world, epochs=args.epochs,
        records=args.records, batch=args.batch_size, seed=args.seed,
        save_every=args.save_every, kill_rank=args.kill_rank,
        kill_step=args.kill_step, lease_s=args.lease_s,
        timeout_s=args.timeout_s, log=_say)
    print(json.dumps(report.summary()))
    if report.ok:
        print(f"resize equivalence OK (multi-process): "
              f"{report.deaths} SIGKILL(s), {report.resizes} "
              f"resize(s) to world {report.final_world} (generation "
              f"{report.final_generation}), {report.records} records "
              f"union-bitwise-identical, detect_s={report.detect_s}, "
              f"time_to_resume_s={report.time_to_resume_s}")
        return 0
    print(f"multi-process drill FAILED: {report.summary()}")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
