"""Unified failure detection — ONE owner for the liveness question.

Before this module the system answered "is that peer alive?" twice,
independently: `ServingRouter` ran a health-poll sweep per router
(PR 9) and `WorldMonitor` did inline heartbeat-lease arithmetic per
member (PR 10). Two detectors means two clocks, two sets of
thresholds, duplicated polling cost on a host running both planes,
and two different failure semantics for the same dead process.

`FailureDetector` centralizes it (docs/resilience.md "Failure
detection"):

* **Graduated suspicion** instead of a binary cliff:
  ``ALIVE -> SUSPECT -> DEAD``. A peer whose evidence is merely stale
  (one dropped heartbeat, a slow poll, a collective-stall report) is
  SUSPECT — consumers *drain* it (route no new work, don't propose it
  out of the world); only evidence stale past the dead threshold is
  DEAD — the verdict that triggers failover/resize.
* **Hysteresis + flap damping**: leaving SUSPECT requires
  ``HVD_DETECTOR_HYSTERESIS`` consecutive good observations, and a
  peer that recovers/re-suspects more than ``HVD_DETECTOR_FLAP_MAX``
  times inside ``HVD_DETECTOR_FLAP_WINDOW_S`` is *damped* — held at
  SUSPECT (drained, not killed) until the window decays — so a
  slow-but-alive peer is never declared dead and resurrected in a
  loop. ``hvd_detector_flaps_total`` is bounded by construction.
* **Pluggable evidence sources** per peer:
  - ``age_fn`` — seconds since the last good proof of life (the KV
    heartbeat lease: `WorldMonitor` registers each member's beat age);
  - ``poll_fn`` — an active probe returning healthy/unhealthy (the
    router registers each replica's ``engine._health()``);
  - **external evidence** — `note_stall` / `ingest_stall_report`
    feed collective-stall attributions from `obs/straggler.py` (a
    rank missing from a timing-window exchange is SUSPECT evidence).
  Evidence *errors* (the KV unreachable, a probe raising) are
  recorded but cap the verdict at SUSPECT: "I cannot see the peer"
  must never read as "the peer is dead" — that asymmetry is the
  split-brain guard the `kv_partition` chaos drill pins.
* **One sweep thread per process** (`shared_detector()`): a host
  running a router fleet *plus* training membership runs exactly one
  ``hvd-failure-detector`` thread, not one liveness loop per
  consumer (pinned by test). Consumers subscribe with
  ``on_transition`` callbacks; callbacks run outside the detector
  lock.
* **Observability**: per-peer ``hvd_detector_*`` metrics,
  ``detector.suspect`` / ``detector.dead`` / ``detector.recovered``
  events, and — on every DEAD verdict — a flight-recorder bundle
  carrying the peer's full evidence timeline (last beats, poll
  results, suspicion transitions), so a postmortem can distinguish
  true death from partition.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.runtime.config import env_float, env_int

from horovod_tpu.analysis import lockcheck

__all__ = ["FailureDetector", "PeerView", "shared_detector",
           "install_detector", "ALIVE", "SUSPECT", "DEAD"]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

# Evidence-timeline depth per peer (the flight-recorder bundle's
# per-peer run-up; small — entries are tiny dicts).
_TIMELINE_DEPTH = 64

# Sweep floor: registrations may ask for faster polls, but the shared
# thread never spins tighter than this.
_MIN_SWEEP_S = 0.005


class PeerView:
    """Read-only snapshot of one peer's detector state (tests/ops)."""

    __slots__ = ("key", "label", "state", "damped", "flaps",
                 "evidence_age_s")

    def __init__(self, key, label, state, damped, flaps, age):
        self.key = key
        self.label = label
        self.state = state
        self.damped = damped
        self.flaps = flaps
        self.evidence_age_s = age


class _Peer:
    """One registered peer: its evidence sources, thresholds, and the
    suspicion state machine's counters. All mutation under the
    detector lock."""

    def __init__(self, key: str, *, label: str,
                 age_fn: Optional[Callable[[], float]],
                 poll_fn: Optional[Callable[[], bool]],
                 clock: Callable[[], float],
                 suspect_after: float, dead_after: float,
                 poll_s: float, hysteresis: int,
                 flap_window_s: float, flap_max: int,
                 rank: Optional[int],
                 on_transition: Optional[Callable]):
        self.key = key
        self.label = label
        self.age_fn = age_fn
        self.poll_fn = poll_fn
        self.clock = clock
        self.suspect_after = float(suspect_after)
        self.dead_after = float(dead_after)
        self.poll_s = max(_MIN_SWEEP_S, float(poll_s))
        self.hysteresis = max(1, int(hysteresis))
        self.flap_window_s = float(flap_window_s)
        self.flap_max = max(1, int(flap_max))
        self.rank = rank
        self.on_transition = on_transition
        self.state = ALIVE
        self.good_streak = 0
        self.flap_times: collections.deque = collections.deque()
        self.flaps = 0
        self.last_age = 0.0
        # Poll-evidence bookkeeping (poll_fn peers).
        self.last_poll_mono = float("-inf")
        self.last_ok_clock = clock()
        self.last_poll_ok = True
        # External (stall-report) negative evidence holds the peer at
        # >= SUSPECT until this clock value.
        self.stall_until = float("-inf")
        self.timeline: collections.deque = collections.deque(
            maxlen=_TIMELINE_DEPTH)

    def note(self, kind: str, **fields):
        self.timeline.append(dict(fields, kind=kind,
                                  t=round(self.clock(), 4)))


# What an evidence source (an age_fn reading a possibly-partitioned
# KV, a poll_fn probing a mid-shutdown engine) may raise and have it
# read as "evidence unavailable" (capped at SUSPECT) instead of
# killing the sweep.
_EVIDENCE_ERRORS = (RuntimeError, ValueError, TypeError, OSError,
                    AttributeError, KeyError)


class FailureDetector:
    """Lease/heartbeat/poll tracking with graduated suspicion for any
    number of registered peers, swept by one background thread
    (module docstring; docs/resilience.md "Failure detection")."""

    def __init__(self, *, sweep_s: Optional[float] = None):
        if sweep_s is None:
            sweep_s = env_float("HVD_DETECTOR_SWEEP_S", 0.05)
        self.sweep_s = max(_MIN_SWEEP_S, float(sweep_s))
        self._lock = lockcheck.register(
            "FailureDetector._lock", threading.Lock())
        self._peers: Dict[str, _Peer] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sweeps = 0

    # -- registration --------------------------------------------------

    def register(self, key: str, *,
                 age_fn: Optional[Callable[[], float]] = None,
                 poll_fn: Optional[Callable[[], bool]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 suspect_after: float,
                 dead_after: float,
                 label: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 hysteresis: Optional[int] = None,
                 flap_window_s: Optional[float] = None,
                 flap_max: Optional[int] = None,
                 rank: Optional[int] = None,
                 on_transition: Optional[Callable] = None) -> str:
        """Register (or re-register) one peer.

        Exactly one evidence source is required: ``age_fn`` returns
        seconds since the peer's last proof of life (lease evidence —
        the caller owns the clock domain, pass the matching
        ``clock``), or ``poll_fn`` actively probes and returns
        healthy. ``on_transition(key, old, new, view)`` fires outside
        the detector lock on every state change. ``rank`` tags the
        peer for `ingest_stall_report` attribution."""
        if (age_fn is None) == (poll_fn is None):
            raise ValueError(
                "register() needs exactly one evidence source "
                "(age_fn OR poll_fn)")
        peer = _Peer(
            key, label=label or key, age_fn=age_fn, poll_fn=poll_fn,
            clock=clock, suspect_after=suspect_after,
            dead_after=dead_after,
            poll_s=poll_s if poll_s is not None else self.sweep_s,
            hysteresis=(hysteresis if hysteresis is not None
                        else env_int("HVD_DETECTOR_HYSTERESIS", 2)),
            flap_window_s=(flap_window_s if flap_window_s is not None
                           else env_float("HVD_DETECTOR_FLAP_WINDOW_S",
                                          30.0)),
            flap_max=(flap_max if flap_max is not None
                      else env_int("HVD_DETECTOR_FLAP_MAX", 4)),
            rank=rank, on_transition=on_transition)
        peer.note("registered")
        with self._lock:
            self._peers[key] = peer
            start = self._thread is None
            if start:
                # Lazily (re)started: a stop()'d detector comes back
                # on the next registration (scoped-test pattern).
                self._stop.clear()
                t = threading.Thread(
                    target=self._sweep_loop,
                    name="hvd-failure-detector", daemon=True)
                self._thread = t
        if start:
            t.start()
        self._wake.set()
        return key

    def unregister(self, key: str) -> None:
        with self._lock:
            self._peers.pop(key, None)

    def unregister_prefix(self, prefix: str) -> None:
        """Drop every peer whose key starts with ``prefix`` (a
        consumer tearing down its whole namespace)."""
        with self._lock:
            for k in [k for k in self._peers if k.startswith(prefix)]:
                del self._peers[k]

    # -- queries -------------------------------------------------------

    def state_of(self, key: str, *, refresh: bool = False) -> str:
        """The peer's suspicion state; ``refresh=True`` evaluates its
        evidence NOW (synchronously, on the caller's thread) instead
        of returning the last sweep's verdict — the resize protocol's
        deterministic read."""
        if refresh:
            fired = self._evaluate_keys([key], force=True)
            self._fire(fired)
        with self._lock:
            p = self._peers.get(key)
            return p.state if p is not None else ALIVE

    def view(self, key: str) -> Optional[PeerView]:
        with self._lock:
            p = self._peers.get(key)
            if p is None:
                return None
            return PeerView(p.key, p.label, p.state,
                            self._damped(p, time.monotonic()),
                            p.flaps, p.last_age)

    def peers(self) -> Dict[str, str]:
        with self._lock:
            return {k: p.state for k, p in self._peers.items()}

    def timeline_of(self, key: str) -> List[Dict]:
        with self._lock:
            p = self._peers.get(key)
            return list(p.timeline) if p is not None else []

    # -- external evidence --------------------------------------------

    def note_stall(self, key: str, *, hold_s: float = 1.0,
                   detail: str = "collective_stall") -> None:
        """Negative external evidence: hold the peer at >= SUSPECT for
        ``hold_s`` (its own clock). Never escalates to DEAD by itself
        — a stall report is a symptom, not a death certificate."""
        with self._lock:
            p = self._peers.get(key)
            if p is None:
                return
            p.stall_until = max(p.stall_until, p.clock() + hold_s)
            p.note("stall", detail=detail, hold_s=hold_s)
        self._wake.set()

    def ingest_stall_report(self, report: Dict, *,
                            hold_s: float = 2.0) -> int:
        """Feed one `obs.straggler.merge_windows` report: every peer
        registered with a ``rank`` in the report's ``missing_ranks``
        (stopped reporting entirely — the usual prime suspect) gets
        stall evidence; a flagged straggler gets a softer note.
        Returns how many peers were marked."""
        missing = set(report.get("missing_ranks") or ())
        slowest = (report.get("slowest_rank")
                   if report.get("straggler") else None)
        marked = 0
        with self._lock:
            targets = [(p, "missing_from_exchange"
                        if p.rank in missing else "straggler")
                       for p in self._peers.values()
                       if p.rank is not None
                       and (p.rank in missing or p.rank == slowest)]
        for p, why in targets:
            self.note_stall(p.key, hold_s=hold_s, detail=why)
            marked += 1
        return marked

    # -- the state machine --------------------------------------------

    def _damped(self, p: _Peer, now_mono: float) -> bool:
        while p.flap_times and now_mono - p.flap_times[0] > p.flap_window_s:
            p.flap_times.popleft()
        return len(p.flap_times) >= p.flap_max

    def _evaluate_keys(self, keys, *, force: bool = False
                       ) -> List[tuple]:
        """Evaluate the named peers' evidence; returns the transition
        callbacks to fire (outside the lock). ``force`` probes poll
        peers even when their poll interval hasn't elapsed (the
        synchronous-refresh path)."""
        now_mono = time.monotonic()
        fired: List[tuple] = []
        # Poll evidence runs OUTSIDE the detector lock (a poll_fn
        # takes engine locks; an age_fn may do a KV round-trip).
        with self._lock:
            peers = [self._peers[k] for k in keys if k in self._peers]
        evidence: Dict[str, tuple] = {}
        due: List[_Peer] = []
        for p in peers:
            fresh = (force
                     or now_mono - p.last_poll_mono >= p.poll_s)
            if not fresh and p.age_fn is not None:
                # Age evidence not due this sweep: hold the peer's
                # state untouched. Gating BOTH evidence kinds on the
                # per-peer poll_s keeps a coexisting fast poll peer
                # (a router replica) from driving every age peer's
                # KV round-trip — and its recovery hysteresis — at
                # the global minimum sweep cadence.
                continue
            due.append(p)
            if not fresh:
                continue   # poll peer ages via ev=None below
            try:
                if p.age_fn is not None:
                    evidence[p.key] = ("age", float(p.age_fn()))
                else:
                    evidence[p.key] = ("poll", bool(p.poll_fn()))
            except _EVIDENCE_ERRORS as e:
                evidence[p.key] = ("error", repr(e))
        with self._lock:
            for p in due:
                if p.key not in self._peers:
                    continue   # unregistered mid-evaluation
                fired.extend(self._apply_evidence(
                    p, evidence.get(p.key), now_mono))
        return fired

    def _apply_evidence(self, p: _Peer, ev, now_mono: float):
        """Fold one evidence observation into the peer's state.
        Returns transition tuples to fire. Lock held."""
        clock_now = p.clock()
        unavailable = False
        if ev is None:
            # Poll not due this sweep: age since the last good poll.
            age = (0.0 if p.last_poll_ok
                   else clock_now - p.last_ok_clock)
        elif ev[0] == "age":
            p.last_poll_mono = now_mono
            age = ev[1]
            if age > p.suspect_after:
                p.note("stale", age_s=round(age, 4))
        elif ev[0] == "poll":
            p.last_poll_mono = now_mono
            p.last_poll_ok = ev[1]
            if ev[1]:
                p.last_ok_clock = clock_now
                age = 0.0
            else:
                age = clock_now - p.last_ok_clock
                p.note("poll_bad", age_s=round(age, 4))
        else:   # evidence error: cannot see the peer
            unavailable = True
            age = p.last_age
            p.note("evidence_error", error=ev[1])
        p.last_age = age
        stalled = clock_now < p.stall_until
        if unavailable:
            # "I can't see the peer" caps at SUSPECT — never DEAD on
            # missing evidence (the split-brain guard) — and never
            # DEMOTES an existing DEAD verdict either: only a real
            # proof of life resurrects a corpse (an observer whose
            # KV flakes mid-resize must not flap a dead member back
            # into the world, re-cutting a flight bundle per flip).
            target = DEAD if p.state == DEAD else SUSPECT
        elif age > p.dead_after:
            target = DEAD
        elif age > p.suspect_after or stalled:
            target = SUSPECT
        else:
            target = ALIVE
        out = []
        if target == ALIVE and p.state != ALIVE:
            # Recovery is hysteresis- and damping-gated; death and
            # suspicion never are (evidence drives them immediately).
            # Only FRESH evidence counts toward the good streak — a
            # cached (ev=None) evaluation re-reading one lucky poll
            # must not satisfy "consecutive good observations".
            if ev is None:
                return out
            p.good_streak += 1
            if (p.good_streak < p.hysteresis
                    or self._damped(p, now_mono)):
                return out
            p.flap_times.append(now_mono)
            p.flaps += 1
            out.append(self._transition(p, ALIVE, age))
            return out
        if target != ALIVE:
            p.good_streak = 0
        if target != p.state:
            out.append(self._transition(p, target, age))
        return out

    def _transition(self, p: _Peer, new: str, age: float) -> tuple:
        old, p.state = p.state, new
        p.note("transition", frm=old, to=new, age_s=round(age, 4))
        return (p, old, new, age)

    # -- the sweep -----------------------------------------------------

    def sweep_once(self) -> None:
        """One evaluation pass over every peer (the background
        thread's body; callable directly from tests)."""
        with self._lock:
            keys = list(self._peers)
            self.sweeps += 1
        self._fire(self._evaluate_keys(keys))
        self._publish_gauges()

    def _fire(self, fired: List[tuple]) -> None:
        """Emit metrics/events/flight bundles and run subscriber
        callbacks for a batch of transitions — all outside the lock
        (callbacks take consumer locks; a DEAD bundle does I/O)."""
        if not fired:
            return
        from horovod_tpu.obs import catalog as _obs_catalog
        from horovod_tpu.obs import events as _events
        from horovod_tpu.obs import flightrec as _flightrec
        m = _obs_catalog.detector_metrics()
        for p, old, new, age in fired:
            m["transitions"].inc(peer=p.label, to=new)
            if new == ALIVE:
                m["flaps"].inc(peer=p.label)
                _events.emit("detector.recovered", peer=p.label,
                             frm=old, flaps=p.flaps)
            elif new == SUSPECT:
                _events.emit("detector.suspect", peer=p.label,
                             frm=old, evidence_age_s=round(age, 4))
            else:
                _events.emit("detector.dead", peer=p.label, frm=old,
                             evidence_age_s=round(age, 4))
                # The postmortem bundle: this peer's full evidence
                # timeline (beats, polls, stalls, transitions), so
                # 03:12-you can tell true death from partition.
                _flightrec.trigger(
                    "detector.dead", peer=p.label, key=p.key,
                    evidence_age_s=round(age, 4),
                    timeline=list(p.timeline))
            cb = p.on_transition
            if cb is not None:
                try:
                    cb(p.key, old, new, self.view(p.key))
                except _EVIDENCE_ERRORS:
                    pass   # a consumer's bug must not kill the sweep

    def _publish_gauges(self) -> None:
        from horovod_tpu.obs import catalog as _obs_catalog
        m = _obs_catalog.detector_metrics()
        with self._lock:
            counts = {ALIVE: 0, SUSPECT: 0, DEAD: 0}
            for p in self._peers.values():
                counts[p.state] += 1
        for state, n in counts.items():
            m["peers"].set(n, state=state)
        m["sweeps"].inc()

    def _interval(self) -> float:
        with self._lock:
            polls = [p.poll_s for p in self._peers.values()]
        return max(_MIN_SWEEP_S,
                   min(polls) if polls else max(0.25, self.sweep_s))

    def _sweep_loop(self):
        while not self._stop.is_set():
            self._wake.wait(self._interval())
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.sweep_once()
            except _EVIDENCE_ERRORS:
                continue   # the detector IS the recovery path

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# The process-shared instance: one sweep thread per host, however many
# routers/monitors consume it.
# ---------------------------------------------------------------------------

_SHARED: Optional[FailureDetector] = None
_SHARED_LOCK = lockcheck.register(
    "detector._SHARED_LOCK", threading.Lock())


def shared_detector() -> FailureDetector:
    """The process-global detector every consumer registers into —
    a host running a router fleet plus training membership gets
    exactly ONE sweep thread."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = FailureDetector()
        return _SHARED


def install_detector(d: Optional[FailureDetector]
                     ) -> Optional[FailureDetector]:
    """Swap the shared detector, returning the previous one (the
    scoped test pattern — same contract as `membership.install_kv`)."""
    global _SHARED
    with _SHARED_LOCK:
        prev, _SHARED = _SHARED, d
        return prev
