"""Crash-restart equivalence: prove exactly-once resumable training.

A checkpointing story is only as good as its proof. This harness runs
the same small training workload twice over a sharded, shuffled
`ShardedDataset`:

* **control** — N epochs uninterrupted, recording a content hash of
  every consumed batch plus the final params/loss;
* **chaos** — the same workload under chaos-injected kills
  (``train_crash`` fires after a step completes but before anything is
  checkpointed — the worst mid-epoch point; ``ckpt_kill`` fires inside
  `save_step` after the staging write but before the atomic rename —
  death *during* a save), each kill followed by a process-like
  restart: a fresh dataset, a fresh `ElasticTrainer`, `resume()` from
  disk.

Equivalence then means: the chaos run's *effective* batch stream (the
batches whose effects survived into the final state — consumed batches
that were rolled past by a restart are trimmed back to the resumed
step) is **bitwise identical** to the control's, and the final params
match to tolerance. With the exact cursor restored,
``resume_gap_batches`` is 0 on every restart — nothing replayed,
nothing skipped.

The training step is deliberately a pure-numpy linear-regression SGD:
bitwise deterministic, no device in the loop, so the harness isolates
exactly what this subsystem owns — data-cursor and snapshot semantics.
(The jax-side resume trajectory is covered by
`tests/test_checkpoint.py` / `tests/test_resilience.py`.)

CI entry (docs/resilience.md "Exact resume")::

    HVD_CHAOS=train_crash:2,ckpt_kill:1 \\
        python -m horovod_tpu.resilience.equivalence --workdir /tmp/eq

`bench.py --resume-check` records the same report (recovery_ms,
resume_gap_batches, kills) as a benchmark artifact entry.

**Resize equivalence** (``--resize``, docs/resilience.md "Elastic
membership"): the elastic twin. A 4-member in-process simulated world
(`resilience.membership.SimulatedWorld` — real heartbeats, real lease
expiry, gradient-averaging lockstep) trains under ``rank_death``: one
member stops heartbeating mid-epoch, the survivors detect the lapsed
lease, commit a new generation, roll back to the last committed
`TrainSnapshot`, and rebalance shards. The proof is at the RECORD
level because a resize regroups batches: the **union** of all
members' effective per-record streams (each log trimmed to its
member's last committed step — the documented rollback gap) must be
bitwise identical, as a multiset, to an uninterrupted control run's.
No record trained twice, none silently dropped. ``rank_death:1,
rank_join:1`` additionally grows the world back and checks the union
across the chained shrink→grow migration. CI entry::

    HVD_CHAOS=rank_death:1 \\
        python -m horovod_tpu.resilience.equivalence --resize \\
        --workdir /tmp/eqr

`bench.py --elastic-check` records the same report as an artifact.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.resilience import chaos
from horovod_tpu.resilience.elastic import ElasticTrainer, NaNGuard

DEFAULT_KILL_SPEC = "train_crash:2,ckpt_kill:1"


@dataclasses.dataclass
class EquivalenceReport:
    """What one crash-restart equivalence run proved (or didn't)."""

    batches_match: bool
    params_match: bool
    kills: int
    resume_gap_batches: int      # max over restarts; 0 = exactly-once
    cursor_fallbacks: int
    recovery_ms: List[float]     # per restart: kill -> resumed
    control_batches: int
    resumed_batches: int
    max_param_delta: float
    control_loss: float
    final_loss: float
    loader: str                  # "native" | "python"
    steps: int

    @property
    def ok(self) -> bool:
        return self.batches_match and self.params_match

    def summary(self) -> Dict:
        """JSON-able digest (the bench artifact / CI log line)."""
        ms = sorted(self.recovery_ms)
        return {
            "ok": self.ok,
            "batches_match": self.batches_match,
            "params_match": self.params_match,
            "kills": self.kills,
            "resume_gap_batches": self.resume_gap_batches,
            "cursor_fallbacks": self.cursor_fallbacks,
            "recovery_ms": {
                "p50": round(ms[len(ms) // 2], 3) if ms else None,
                "max": round(ms[-1], 3) if ms else None,
            },
            "batches": self.resumed_batches,
            "steps": self.steps,
            "max_param_delta": float(self.max_param_delta),
            "loader": self.loader,
        }


def _batch_key(batch: Dict[str, np.ndarray]) -> str:
    """Content hash of one batch — field names, dtypes, shapes, and
    raw bytes all participate, so "bitwise identical" means exactly
    that."""
    h = hashlib.sha256()
    for name in sorted(batch):
        a = np.ascontiguousarray(batch[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _default_step(state: Dict[str, np.ndarray],
                  batch: Dict[str, np.ndarray],
                  lr: float = 0.05
                  ) -> Tuple[Dict[str, np.ndarray], float]:
    """Pure-numpy linear-regression SGD step — bitwise deterministic
    given (state, batch)."""
    x = batch["x"].astype(np.float64)
    y = batch["y"].astype(np.float64)
    pred = x @ state["w"] + state["b"]
    err = pred - y
    gw = x.T @ err / len(y)
    gb = err.mean()
    new = {"w": state["w"] - lr * gw,
           "b": state["b"] - lr * gb}
    return new, float((err ** 2).mean())


def _write_dataset(workdir: str, *, records: int, dim: int,
                   num_shards: int, seed: int):
    from horovod_tpu import data as hd
    spec = [("x", "float32", (dim,)), ("y", "float32", ())]
    rs = np.random.RandomState(seed)
    w_true = rs.randn(dim).astype(np.float32)
    x = rs.randn(records, dim).astype(np.float32)
    y = (x @ w_true + 0.01 * rs.randn(records)).astype(np.float32)
    paths = hd.write_shards(os.path.join(workdir, "shards"), "eq",
                            spec, {"x": x, "y": y}, num_shards)
    return paths, spec


def run_crash_restart_equivalence(
        workdir: str, *,
        epochs: int = 3,
        records: int = 48,
        batch_size: int = 4,
        dim: int = 3,
        num_shards: int = 3,
        save_every: int = 2,
        seed: int = 11,
        kill_spec: str = DEFAULT_KILL_SPEC,
        use_native: Optional[bool] = None,
        tol: float = 1e-9,
        max_restarts: int = 64,
        step_fn: Callable = _default_step,
        log: Optional[Callable[[str], None]] = None,
) -> EquivalenceReport:
    """Train-twice, kill-once(-or-more), assert-equivalent.

    ``use_native``: pin the loader implementation (None = whatever
    `ShardedDataset` resolves; tests run both). ``kill_spec`` arms the
    kill sites for the chaos leg — unless a monkey is ALREADY
    installed (e.g. the CI smoke's ``HVD_CHAOS`` env arming), which
    then takes precedence so the harness composes with external chaos
    drills. The control leg always runs disarmed.

    Raises `RuntimeError` if the chaos leg cannot finish within
    ``max_restarts`` restarts (an armed unbounded kill site would
    otherwise loop forever).
    """
    from horovod_tpu import data as hd
    from horovod_tpu.runtime.config import config

    def say(msg):
        if log is not None:
            log(msg)

    os.makedirs(workdir, exist_ok=True)
    paths, spec = _write_dataset(workdir, records=records, dim=dim,
                                 num_shards=num_shards, seed=seed)
    state0 = {"w": np.zeros(dim, np.float64), "b": np.float64(0.0)}

    prev_native = config.use_native
    if use_native is not None:
        config.use_native = use_native

    def make_ds():
        return hd.ShardedDataset(paths, spec, batch_size, shuffle=True,
                                 seed=seed, rank=0, world=1)

    cursor_fallbacks = [0]   # mutated by run_leg across restarts
    gaps_seen: List[int] = []
    recovery_ms: List[float] = []
    used_native = [False]    # observed from the live legs' datasets

    def run_leg(ckpt_dir: str, stream: List[str],
                kill_t: Optional[float] = None
                ) -> Tuple[Dict, float, int]:
        """One process lifetime: resume (fresh everything), trim the
        stream to the resumed step, train to the end. Returns
        (final_state, final_loss, steps)."""
        with make_ds() as ds:
            used_native[0] = bool(ds.native)
            trainer = ElasticTrainer(
                ckpt_dir, save_every=save_every, keep=0, block=True,
                install_signals=False, dataset=ds, guard=NaNGuard())
            state, step = trainer.resume(like=state0)
            if kill_t is not None:
                # The operator-felt number: simulated process death to
                # full TrainSnapshot reconstruction.
                recovery_ms.append((time.time() - kill_t) * 1e3)
            gaps_seen.append(int(trainer.resume_gap_batches))
            cursor_fallbacks[0] += trainer.cursor_fallbacks
            # Batches consumed after the last snapshot died with the
            # process; their effects are NOT in `state`. Trim so the
            # stream records exactly the batches that built the final
            # params.
            del stream[step:]
            e0, b0 = trainer.data_start
            loss = float("nan")
            for epoch in range(e0, epochs):
                sb = b0 if epoch == e0 else 0
                for batch in ds.epoch(epoch, start_batch=sb):
                    state, loss = step_fn(state, batch)
                    step += 1
                    stream.append(_batch_key(batch))
                    state = trainer.after_step(step, state, loss)
            return state, loss, step

    try:
        # -- control: uninterrupted, chaos disarmed ---------------------
        prev_monkey = chaos.active()   # NOT install(None)'s return —
        chaos.install(None)            # install returns the NEW value
        try:
            control_stream: List[str] = []
            control_state, control_loss, control_steps = run_leg(
                os.path.join(workdir, "ckpt_control"), control_stream)
        finally:
            chaos.install(prev_monkey)
        say(f"control: {control_steps} steps, "
            f"{len(control_stream)} batches, loss {control_loss:.6f}")

        # -- chaos leg: kills + restarts --------------------------------
        monkey = (prev_monkey if prev_monkey is not None
                  else chaos.ChaosMonkey(kill_spec, seed=seed))
        chaos.install(monkey)
        cursor_fallbacks[0] = 0
        gaps_seen.clear()
        stream: List[str] = []
        kills = 0
        kill_t: Optional[float] = None
        try:
            while True:
                try:
                    final_state, final_loss, steps = run_leg(
                        os.path.join(workdir, "ckpt_chaos"), stream,
                        kill_t)
                    break
                except chaos.ChaosError as e:
                    kills += 1
                    kill_t = time.time()
                    say(f"kill #{kills}: {e}")
                    if kills > max_restarts:
                        raise RuntimeError(
                            f"chaos leg did not converge within "
                            f"{max_restarts} restarts — is an "
                            f"unbounded kill site armed?") from e
        finally:
            chaos.install(prev_monkey)
        gap_max = max(gaps_seen) if gaps_seen else 0
        say(f"chaos: {kills} kill(s), {steps} steps, "
            f"{len(stream)} effective batches, loss {final_loss:.6f}")

        batches_match = stream == control_stream
        deltas = [np.max(np.abs(np.asarray(final_state[k])
                                - np.asarray(control_state[k])))
                  for k in control_state]
        max_delta = float(max(deltas)) if deltas else 0.0
        params_match = max_delta <= tol
        return EquivalenceReport(
            batches_match=batches_match,
            params_match=params_match,
            kills=kills,
            resume_gap_batches=gap_max,
            cursor_fallbacks=cursor_fallbacks[0],
            recovery_ms=recovery_ms,
            control_batches=len(control_stream),
            resumed_batches=len(stream),
            max_param_delta=max_delta,
            control_loss=control_loss,
            final_loss=final_loss,
            loader="native" if used_native[0] else "python",
            steps=steps,
        )
    finally:
        config.use_native = prev_native


# ---------------------------------------------------------------------------
# Resize equivalence (elastic membership).
# ---------------------------------------------------------------------------

DEFAULT_RESIZE_KILL_SPEC = "rank_death:1"


@dataclasses.dataclass
class ResizeEquivalenceReport:
    """What one elastic shrink(/grow) equivalence run proved."""

    union_match: bool
    completed: bool              # both legs finished every epoch
    deaths: int
    joins: int
    resizes: int
    final_world: int
    final_generation: int
    control_records: int
    resized_records: int         # effective union size, chaos leg
    records_reassigned: int
    detect_s: Dict               # p50/max: member death -> resumed
    time_to_resume_s: Dict       # p50/max: detection -> resumed
    loader: str
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return (self.union_match and self.completed
                and self.resizes >= 1 and self.deaths >= 1)

    def summary(self) -> Dict:
        return {
            "ok": self.ok,
            "union_match": self.union_match,
            "completed": self.completed,
            "deaths": self.deaths,
            "joins": self.joins,
            "resizes": self.resizes,
            "final_world": self.final_world,
            "final_generation": self.final_generation,
            "records": self.resized_records,
            "records_reassigned": self.records_reassigned,
            "detect_s": self.detect_s,
            "time_to_resume_s": self.time_to_resume_s,
            "loader": self.loader,
            "error": self.error,
        }


def _elastic_grad(state: Dict[str, np.ndarray],
                  batch: Dict[str, np.ndarray]):
    """Gradient leg of the pure-numpy SGD step (`_default_step`'s
    math split so the simulated world can average across members)."""
    x = batch["x"].astype(np.float64)
    y = batch["y"].astype(np.float64)
    err = x @ state["w"] + state["b"] - y
    return ({"w": x.T @ err / len(y), "b": np.float64(err.mean())},
            float((err ** 2).mean()))


def _elastic_apply(state: Dict[str, np.ndarray], grads: Dict,
                   lr: float = 0.05) -> Dict[str, np.ndarray]:
    return {"w": state["w"] - lr * grads["w"],
            "b": state["b"] - lr * np.float64(grads["b"])}


def run_resize_equivalence(
        workdir: str, *,
        world: int = 4,
        epochs: int = 2,
        records: int = 64,
        batch_size: int = 4,
        dim: int = 3,
        num_shards: int = 4,
        save_every: int = 2,
        seed: int = 11,
        kill_spec: str = DEFAULT_RESIZE_KILL_SPEC,
        lease_s: float = 0.35,
        use_native: Optional[bool] = None,
        timeout_s: float = 180.0,
        log: Optional[Callable[[str], None]] = None,
) -> ResizeEquivalenceReport:
    """Train the elastic world twice — uninterrupted control vs a
    chaos leg under ``kill_spec`` (an ALREADY-installed monkey, e.g.
    the CI smoke's ``HVD_CHAOS`` env arming, takes precedence; the
    control leg always runs disarmed) — and assert the effective
    per-record union streams are bitwise identical multisets."""
    from horovod_tpu import data as hd
    from horovod_tpu.resilience.membership import SimulatedWorld
    from horovod_tpu.runtime.config import config

    def say(msg):
        if log is not None:
            log(msg)

    os.makedirs(workdir, exist_ok=True)
    paths, spec = _write_dataset(workdir, records=records, dim=dim,
                                 num_shards=num_shards, seed=seed)
    state0 = {"w": np.zeros(dim, np.float64), "b": np.float64(0.0)}
    used_native = [False]

    prev_native = config.use_native
    if use_native is not None:
        config.use_native = use_native

    def make_ds(rank, w):
        ds = hd.ShardedDataset(paths, spec, batch_size, shuffle=True,
                               seed=seed, rank=rank, world=w)
        used_native[0] = bool(ds.native)
        return ds

    def run_leg(ckpt_sub):
        return SimulatedWorld(
            world=world, make_dataset=make_ds, state0=state0,
            grad_fn=_elastic_grad, apply_fn=_elastic_apply,
            ckpt_dir=os.path.join(workdir, ckpt_sub), epochs=epochs,
            save_every=save_every, lease_s=lease_s,
        ).run(timeout_s=timeout_s)

    try:
        prev_monkey = chaos.active()   # NOT install(None)'s return —
        chaos.install(None)            # install returns the NEW value
        try:
            control = run_leg("ckpt_control")
        finally:
            chaos.install(prev_monkey)
        say(f"control: {control.summary()}")

        monkey = (prev_monkey if prev_monkey is not None
                  else chaos.ChaosMonkey(kill_spec, seed=seed))
        chaos.install(monkey)
        try:
            resized = run_leg("ckpt_chaos")
        finally:
            chaos.install(prev_monkey)
        say(f"chaos: {resized.summary()}")

        control_union = control.union_keys()
        resized_union = resized.union_keys()
        errors = [e for e in (control.error, resized.error) if e]
        return ResizeEquivalenceReport(
            union_match=(control_union == resized_union),
            completed=(control.completed and resized.completed),
            deaths=len(resized.deaths),
            joins=len(resized.joins),
            resizes=len(resized.resizes),
            final_world=resized.final_world,
            final_generation=resized.final_generation,
            control_records=len(control_union),
            resized_records=len(resized_union),
            records_reassigned=sum(
                r.get("records_reassigned", 0)
                for r in resized.resizes),
            detect_s=resized.summary()["detect_s"],
            time_to_resume_s=resized.summary()["time_to_resume_s"],
            loader="native" if used_native[0] else "python",
            error="; ".join(errors) if errors else None,
        )
    finally:
        config.use_native = prev_native


def main(argv=None) -> int:
    """CI smoke entry: run the harness once, print the report, exit
    nonzero unless the run proved equivalence with a zero resume gap
    AND at least one kill actually fired (a smoke whose chaos never
    triggered proves nothing)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="chaos-driven crash-restart equivalence check")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--resize", action="store_true",
                    help="run the ELASTIC resize equivalence instead: "
                         "a 4-member simulated world under rank_death "
                         "must shrink, rebalance, and finish with the "
                         "untrained-remainder union bitwise-equal to "
                         "an uninterrupted run's")
    ap.add_argument("--world", type=int, default=4,
                    help="--resize: launch world size")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--lease-s", type=float, default=0.35,
                    help="--resize: heartbeat lease for the simulated "
                         "world")
    ap.add_argument("--kill-spec", default=None,
                    help="chaos sites for the kill leg (an installed "
                         "HVD_CHAOS monkey takes precedence; default "
                         f"'{DEFAULT_KILL_SPEC}', or "
                         f"'{DEFAULT_RESIZE_KILL_SPEC}' with --resize)")
    ap.add_argument("--loader", default="auto",
                    choices=["auto", "native", "python"],
                    help="pin the ShardedDataset implementation")
    args = ap.parse_args(argv)

    use_native = {"auto": None, "native": True,
                  "python": False}[args.loader]
    if args.resize:
        rreport = run_resize_equivalence(
            args.workdir, world=args.world,
            epochs=max(2, args.epochs - 1), records=args.records + 16,
            batch_size=args.batch_size, save_every=args.save_every,
            seed=args.seed,
            kill_spec=args.kill_spec or DEFAULT_RESIZE_KILL_SPEC,
            lease_s=args.lease_s, use_native=use_native, log=print)
        print(json.dumps(rreport.summary()))
        if rreport.ok:
            print(f"resize equivalence OK: {rreport.deaths} death(s),"
                  f" {rreport.joins} join(s), {rreport.resizes} "
                  f"resize(s) to world {rreport.final_world} "
                  f"(generation {rreport.final_generation}), "
                  f"{rreport.resized_records} records union-bitwise-"
                  f"identical, {rreport.records_reassigned} "
                  f"reassigned")
            return 0
        print(f"resize equivalence FAILED: {rreport.summary()}")
        return 1
    report = run_crash_restart_equivalence(
        args.workdir, epochs=args.epochs, records=args.records,
        batch_size=args.batch_size, save_every=args.save_every,
        seed=args.seed, kill_spec=args.kill_spec or DEFAULT_KILL_SPEC,
        use_native=use_native, log=print)
    print(json.dumps(report.summary()))
    if report.ok and report.resume_gap_batches == 0 and report.kills:
        print(f"equivalence OK: {report.kills} kill(s), "
              f"{report.resumed_batches} batches bitwise-identical, "
              f"max param delta {report.max_param_delta:.2e}, "
              f"resume gap 0")
        return 0
    print(f"equivalence FAILED: {report.summary()}")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
