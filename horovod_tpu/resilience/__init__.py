"""`horovod_tpu.resilience` — fault injection, retry, and
preemption-safe training.

Horovod's only failure story is "warn after 60 s and hope"
(`CheckForStalledTensors`, mirrored in `utils/stall.py`). On TPU pods
preemption is a scheduled fact of life and a single hung collective
stalls the whole mesh, so this package gives the repo a tested
recovery layer (docs/resilience.md):

* `chaos` — a `ChaosMonkey` fault injector armed via ``HVD_CHAOS``;
  named sites instrument checkpoint I/O, collectives, the train step,
  and the serving engine with zero-overhead-when-disabled hooks, with
  deterministic seeding so failures replay.
* `retry` — `RetryPolicy`: bounded exponential backoff with jitter
  and a deadline, shared by checkpoint I/O and data loading.
* `elastic` — `PreemptionHandler` (SIGTERM/SIGINT emergency
  checkpoint), `NaNGuard` (loss-spike / NaN rollback), and
  `ElasticTrainer` tying resume discovery, periodic + emergency
  checkpointing, rollback, and the exactly-once `TrainSnapshot`
  (model + optimizer + data cursor + host RNG + guard history) into
  one loop-side helper.
* `membership` — elastic world membership: `WorldMonitor` heartbeat
  leases + rank-death/join detection over a pluggable KV transport,
  the barrier'd propose/ack/commit resize protocol (monotonic world
  generation), and `SimulatedWorld`, the in-process N-thread elastic
  training world CPU tests drive end-to-end (docs/resilience.md
  "Elastic membership").
* `equivalence` — the crash-restart equivalence harness: trains the
  same workload twice, once uninterrupted and once under
  chaos-injected kills + restarts, and asserts the batch streams are
  bitwise identical and the final params match (``python -m
  horovod_tpu.resilience.equivalence`` is the CI smoke entry); with
  ``--resize``, the elastic twin — a simulated world under
  ``rank_death`` must shrink, rebalance, and keep the per-record
  union stream bitwise-equal to an uninterrupted run's.

The chaos-vs-recovery contract is exercised end-to-end in
`tests/test_resilience.py` / `tests/test_resume.py`: every recovery
path in this package is driven by an injected fault, not asserted.
"""

from horovod_tpu.resilience.chaos import (
    ChaosError,
    ChaosMonkey,
    armed,
    fires,
)
from horovod_tpu.resilience.detector import (
    FailureDetector,
    install_detector,
    shared_detector,
)
from horovod_tpu.resilience.elastic import (
    ElasticTrainer,
    NaNGuard,
    PreemptionHandler,
    TrainSnapshot,
)
from horovod_tpu.resilience.membership import (
    BootstrapKV,
    ChaosKV,
    ElasticBarrier,
    InProcessKV,
    KVTransportError,
    MembershipError,
    ResizeDecision,
    SimulatedWorld,
    WorldMonitor,
    install_kv,
)
from horovod_tpu.resilience.retry import (
    RetryError,
    RetryPolicy,
    default_io_policy,
)

__all__ = [
    "ChaosError", "ChaosMonkey", "armed", "fires",
    "FailureDetector", "install_detector", "shared_detector",
    "RetryError", "RetryPolicy", "default_io_policy",
    "ElasticTrainer", "NaNGuard", "PreemptionHandler",
    "TrainSnapshot",
    "BootstrapKV", "ChaosKV", "ElasticBarrier", "InProcessKV",
    "KVTransportError", "MembershipError", "ResizeDecision",
    "SimulatedWorld", "WorldMonitor", "install_kv",
]
