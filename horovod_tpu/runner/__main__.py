import sys

from horovod_tpu.runner import main

if __name__ == "__main__":
    sys.exit(main())
