"""hvdrun — the launcher.

Replacement for the reference's `mpirun -np N python train.py` contract
(`README.md:125-135`, SURVEY §7 step 6): spawns N worker processes,
wires the process group (rank/size/local placement env vars), runs the
native TCP rendezvous (key-value + barrier) that replaces the MPI
control plane, and points workers at a JAX coordination service for
`jax.distributed.initialize`.

Usage:
    hvdrun -np 4 python train.py ...
    hvdrun -np 2 --platform cpu python train.py

Multi-host (the reference's `mpirun -H server1:4,server2:4` contract,
`README.md:136-144`): run one hvdrun per host with the same slot map.
Host 0 serves the shared rendezvous; the others point at it:

    # on server1 (hosts rank 0; serves the KV/barrier plane)
    hvdrun -H server1:4,server2:4 --host-index 0 --kv-port 29500 \
           python train.py
    # on server2
    hvdrun -H server1:4,server2:4 --host-index 1 \
           --rendezvous server1:29500 python train.py

Each instance launches only its own host's slots with global rank
offsets; the env-var contract (HOROVOD_RANK / SIZE / LOCAL_RANK /
LOCAL_SIZE / COORDINATOR / KV) is identical either way. (TPU pods
usually skip hvdrun entirely: the pod runtime provides the process
group and `hvd.init()` attaches to it.)
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from typing import List


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, ""):
        out.write(f"[{prefix}] {line}")
        out.flush()
    pipe.close()


def _parse_hosts(spec: str):
    """'server1:4,server2:4' -> [('server1', 4), ('server2', 4)]
    (reference `mpirun -H` slot syntax, README.md:136-144)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, slots = part.partition(":")
        n = int(slots) if sep else 1
        if not host or n < 1:
            raise ValueError(f"bad host entry {part!r} (need host:n "
                             f"with n >= 1)")
        out.append((host, n))
    if not out:
        raise ValueError(f"empty host spec {spec!r}")
    return out


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch N horovod_tpu worker processes (mpirun "
                    "replacement).")
    ap.add_argument("-np", "--num-proc", type=int, default=None,
                    help="total worker processes across all hosts "
                         "(default: sum of -H slots)")
    ap.add_argument("-H", "--hosts", default=None,
                    help="host1:n,host2:n slot map; this instance "
                         "launches only the --host-index entry's slots "
                         "with global rank offsets")
    ap.add_argument("--host-index", type=int, default=0,
                    help="which -H entry this instance is")
    ap.add_argument("--rendezvous", default=None, metavar="HOST:PORT",
                    help="KV/barrier server of host 0 (non-zero hosts "
                         "connect instead of serving)")
    ap.add_argument("--kv-port", type=int, default=0,
                    help="fixed port for the rendezvous server on host "
                         "0 (default: any free port)")
    ap.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="jax.distributed coordinator address; must be "
                         "the same on every host (default: a free port "
                         "on this host — fine single-host)")
    ap.add_argument("--platform", default="cpu",
                    choices=["cpu", "tpu", "auto"],
                    help="JAX platform forced in workers (cpu default: "
                         "single-host TPU boxes have one chip, so "
                         "multi-process means CPU devices)")
    ap.add_argument("--devices-per-proc", type=int, default=1,
                    help="virtual CPU devices per worker (cpu platform)")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic job: a worker killed by a SIGNAL "
                         "(preemption, the elastic drill's SIGKILL) "
                         "does NOT kill the job — survivors keep "
                         "running and the exit code reflects them; a "
                         "worker failing with a nonzero STATUS still "
                         "fails the job (mpirun's all-or-nothing "
                         "contract stays the default)")
    ap.add_argument("--no-prefix", action="store_true",
                    help="don't prefix worker output with [rank]")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command, e.g. python train.py")
    args = ap.parse_args(argv)

    if not args.command:
        ap.error("missing worker command")
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    # Resolve this instance's slice of the world.
    if args.hosts is not None:
        try:
            hosts = _parse_hosts(args.hosts)
        except ValueError as e:
            ap.error(str(e))
        total = sum(s for _, s in hosts)
        if args.num_proc is not None and args.num_proc != total:
            ap.error(f"-np {args.num_proc} != sum of -H slots {total}")
        if not 0 <= args.host_index < len(hosts):
            ap.error(f"--host-index {args.host_index} out of range for "
                     f"{len(hosts)} hosts")
        rank_offset = sum(s for _, s in hosts[:args.host_index])
        local_n = hosts[args.host_index][1]
        head_host = hosts[0][0]
    else:
        if args.num_proc is None:
            ap.error("need -np or -H")
        if args.host_index != 0 or args.rendezvous is not None:
            # Without a slot map there are no rank offsets: a second
            # instance would relaunch ranks 0..n-1 and corrupt the
            # process group.
            ap.error("--host-index/--rendezvous require -H")
        total = local_n = args.num_proc
        rank_offset = 0
        head_host = "127.0.0.1"

    serve_here = args.rendezvous is None and args.host_index == 0
    if (args.hosts is not None and len(hosts) > 1
            and not args.coordinator):
        # Each instance would pick an independent random port for the
        # jax.distributed coordinator — guaranteed cross-host hang.
        ap.error("multi-host launch requires --coordinator HOST:PORT "
                 "(the same value on every host)")
    coord_addr = args.coordinator or f"{head_host}:{_free_port()}"

    native = None
    if serve_here:
        # The launcher hosts the rendezvous server (the rank-0
        # coordinator role of the reference's background thread,
        # mpi_ops.cc:1316-1371). Barrier membership is the TOTAL world,
        # so multi-host instances meet at the same server.
        from horovod_tpu.native import load_native
        native = load_native()
        bound = native.serve(args.kv_port or _free_port(), total)
        if bound <= 0:
            print("hvdrun: failed to start rendezvous server",
                  file=sys.stderr)
            return 1
        kv_addr = f"{head_host}:{bound}" if args.hosts else \
            f"127.0.0.1:{bound}"
        if args.hosts is not None and len(hosts) > 1:
            # Other hosts must be pointed at this exact address; with
            # an ephemeral port (no --kv-port) they can't guess it.
            print(f"hvdrun: rendezvous serving at {kv_addr} — start "
                  f"the other hosts with --rendezvous {kv_addr}",
                  file=sys.stderr)
            if not args.kv_port:
                print("hvdrun: warning: no --kv-port given; the port "
                      "above is ephemeral and differs every run",
                      file=sys.stderr)
    else:
        if args.rendezvous is None:
            ap.error("non-zero --host-index needs --rendezvous "
                     "(host 0's KV address)")
        kv_addr = args.rendezvous

    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    for local_rank in range(local_n):
        rank = rank_offset + local_rank
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(total),
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_LOCAL_SIZE": str(local_n),
            "HOROVOD_COORDINATOR": coord_addr,
            "HOROVOD_KV": kv_addr,
        })
        if args.platform != "auto":
            env["HOROVOD_PLATFORM"] = args.platform
        if args.platform == "cpu":
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{args.devices_per_proc}").strip()
        p = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.PIPE if not args.no_prefix else None,
            stderr=subprocess.STDOUT if not args.no_prefix else None,
            text=not args.no_prefix)
        procs.append(p)
        if not args.no_prefix:
            t = threading.Thread(target=_stream,
                                 args=(str(rank), p.stdout, sys.stdout),
                                 daemon=True)
            t.start()
            threads.append(t)

    exit_code = 0
    clean_exits = 0
    try:
        remaining = set(range(local_n))
        while remaining:
            for i in list(remaining):
                rc = procs[i].poll()
                if rc is None:
                    continue
                remaining.discard(i)
                if rc == 0:
                    clean_exits += 1
                    continue
                if args.elastic and rc < 0:
                    # Elastic contract: a signal death (preemption,
                    # SIGKILL drill) is a MEMBERSHIP event, not a job
                    # failure — the survivors' resize protocol owns
                    # it from here.
                    print(f"hvdrun: worker {rank_offset + i} died "
                          f"with signal {-rc}; elastic job continues",
                          file=sys.stderr)
                    continue
                if exit_code == 0:
                    exit_code = rc
                    if not args.elastic:
                        # mpirun behavior: one failure kills the job.
                        for j in remaining:
                            procs[j].terminate()
            if remaining:
                import time
                time.sleep(0.2)
        if args.elastic and exit_code == 0 and clean_exits == 0:
            # Every worker died by signal: nobody survived to finish
            # the job — that is a failure, not elasticity.
            exit_code = 1
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=2)
        if native is not None:
            native.serve_stop()
    return exit_code
