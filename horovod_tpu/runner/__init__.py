"""hvdrun — the launcher.

Replacement for the reference's `mpirun -np N python train.py` contract
(`README.md:125-135`, SURVEY §7 step 6): spawns N worker processes,
wires the process group (rank/size/local placement env vars), runs the
native TCP rendezvous (key-value + barrier) that replaces the MPI
control plane, and points workers at a JAX coordination service for
`jax.distributed.initialize`.

Usage:
    python -m horovod_tpu.runner -np 4 python train.py ...
    python -m horovod_tpu.runner -np 2 --platform cpu python train.py

Single-host today; the env-var contract (HOROVOD_RANK / SIZE /
LOCAL_RANK / LOCAL_SIZE / COORDINATOR / KV) is host-agnostic, so a
multi-host wrapper only needs to start this per host with the right
rank offsets (TPU pods usually skip hvdrun entirely: the pod runtime
provides the process group and `hvd.init()` attaches to it).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from typing import List


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, ""):
        out.write(f"[{prefix}] {line}")
        out.flush()
    pipe.close()


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch N horovod_tpu worker processes (mpirun "
                    "replacement).")
    ap.add_argument("-np", "--num-proc", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("--platform", default="cpu",
                    choices=["cpu", "tpu", "auto"],
                    help="JAX platform forced in workers (cpu default: "
                         "single-host TPU boxes have one chip, so "
                         "multi-process means CPU devices)")
    ap.add_argument("--devices-per-proc", type=int, default=1,
                    help="virtual CPU devices per worker (cpu platform)")
    ap.add_argument("--no-prefix", action="store_true",
                    help="don't prefix worker output with [rank]")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command, e.g. python train.py")
    args = ap.parse_args(argv)

    if not args.command:
        ap.error("missing worker command")
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    n = args.num_proc
    jax_port = _free_port()
    kv_port = _free_port()

    # The launcher hosts the rendezvous server (the rank-0 coordinator
    # role of the reference's background thread, mpi_ops.cc:1316-1371).
    from horovod_tpu.native import load_native
    native = load_native()
    bound = native.serve(kv_port, n)
    if bound <= 0:
        print("hvdrun: failed to start rendezvous server", file=sys.stderr)
        return 1

    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_LOCAL_RANK": str(rank),
            "HOROVOD_LOCAL_SIZE": str(n),
            "HOROVOD_COORDINATOR": f"127.0.0.1:{jax_port}",
            "HOROVOD_KV": f"127.0.0.1:{bound}",
        })
        if args.platform != "auto":
            env["HOROVOD_PLATFORM"] = args.platform
        if args.platform == "cpu":
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{args.devices_per_proc}").strip()
        p = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.PIPE if not args.no_prefix else None,
            stderr=subprocess.STDOUT if not args.no_prefix else None,
            text=not args.no_prefix)
        procs.append(p)
        if not args.no_prefix:
            t = threading.Thread(target=_stream,
                                 args=(str(rank), p.stdout, sys.stdout),
                                 daemon=True)
            t.start()
            threads.append(t)

    exit_code = 0
    try:
        remaining = set(range(n))
        while remaining:
            for i in list(remaining):
                rc = procs[i].poll()
                if rc is None:
                    continue
                remaining.discard(i)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    # mpirun behavior: one failure kills the job.
                    for j in remaining:
                        procs[j].terminate()
            if remaining:
                import time
                time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=2)
        native.serve_stop()
    return exit_code
