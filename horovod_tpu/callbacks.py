"""Training-loop callbacks (framework-neutral core).

JAX-side equivalents of the reference's Keras callbacks
(`horovod/keras/callbacks.py`); the Keras adapter re-exports thin
wrappers around these.

* `lr_warmup_schedule` — gradual LR warmup per Goyal et al. 2017
  (`callbacks.py:89-178`): lr'(epoch) = lr * (epoch*(size-1)/warmup + 1),
  so lr'(0)=lr and lr'(warmup)=size*lr. Returned as an optax schedule
  (step-indexed), the idiomatic JAX home for LR policy.
* `MetricAverager` — allreduce-averages a metrics dict across workers at
  epoch end, sorted by name for deterministic collective order
  (`callbacks.py:37-86`).
* `broadcast_on_train_begin` — the BroadcastGlobalVariablesCallback
  contract (`callbacks.py:8-34`).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from horovod_tpu.runtime import state as _state


def lr_warmup_schedule(base_lr: float, warmup_epochs: int = 5,
                       steps_per_epoch: int = 1,
                       size: Optional[int] = None):
    """optax-compatible schedule implementing the reference warmup math
    (`horovod/keras/callbacks.py:96-104`). After `warmup_epochs` the LR
    stays at size*base_lr (compose with any decay schedule after)."""
    st = _state.check_initialized()
    n = size if size is not None else st.size

    def schedule(step):
        import jax.numpy as jnp
        epoch = step / steps_per_epoch
        scale = jnp.minimum(epoch, warmup_epochs) * (n - 1) / warmup_epochs + 1
        return base_lr * scale

    return schedule


class MetricAverager:
    """Average metric values across workers at epoch end
    (`horovod/keras/callbacks.py:37-86`)."""

    def __init__(self):
        self._st = _state.check_initialized()

    def __call__(self, logs: Dict[str, float]) -> Dict[str, float]:
        from horovod_tpu.jax import grouped_allreduce
        out = dict(logs)
        # Sorted for deterministic collective order across ranks
        # (callbacks.py:71-72); one fused collective for all metrics
        # instead of one per metric.
        keys = sorted(logs)
        if not keys:
            return out
        vals = grouped_allreduce(
            [np.asarray(logs[k], np.float64) for k in keys],
            average=True, name="metric_avg")
        for k, v in zip(keys, vals):
            out[k] = float(np.asarray(v))
        return out


def broadcast_on_train_begin(params, root_rank: int = 0):
    """Alias for broadcast_global_variables with callback naming."""
    from horovod_tpu.jax import broadcast_global_variables
    return broadcast_global_variables(params, root_rank)


class ResilientCheckpointCallback:
    """Keras-style step/epoch-end callback over
    `resilience.ElasticTrainer`: periodic atomic checkpoints, an
    emergency save the moment SIGTERM/SIGINT lands, and NaN/loss-spike
    rollback to the last good checkpoint (docs/resilience.md).

    ::

        cb = ResilientCheckpointCallback("/ckpts", save_every=50)
        state, start = cb.resume(like=state)
        for i in range(start, steps):
            state, loss = step(state, batch())
            state = cb(i + 1, state, loss)
            if cb.should_stop:
                break
    """

    def __init__(self, directory: str, *, save_every: int = 50,
                 keep: int = 3, block: bool = False,
                 install_signals: bool = True):
        from horovod_tpu.resilience import ElasticTrainer
        self._trainer = ElasticTrainer(
            directory, save_every=save_every, keep=keep, block=block,
            install_signals=install_signals)

    def resume(self, *, like=None, broadcast: bool = False):
        return self._trainer.resume(like=like, broadcast=broadcast)

    def __call__(self, step: int, state, loss):
        return self._trainer.after_step(step, state, loss)

    @property
    def should_stop(self) -> bool:
        return self._trainer.should_stop

    @property
    def rollbacks(self) -> int:
        return self._trainer.rollbacks

    def close(self):
        """Uninstall the signal handlers (see ElasticTrainer.close)."""
        self._trainer.close()
