"""Tensor fusion — bucketed gradient allreduce.

TPU-native translation of the reference's Tensor Fusion (SURVEY C5;
`docs/tensor-fusion.md:7-28`, fusion buffer `mpi_ops.cc:667-700`,
response merging `mpi_ops.cc:1392-1419`): many small gradients are batched
into one collective to amortize per-collective latency. Where the
reference memcpys into a persistent 64 MB device buffer, here each bucket
is a flat concatenation of raveled leaves — XLA fuses the concat/split
with neighboring ops, so the "fusion buffer" never exists as a separate
copy in HBM — followed by ONE psum per bucket.

Buckets group leaves by dtype (the reference fuses only same-dtype
responses, `mpi_ops.cc:1397-1404`) and close at
`HOROVOD_FUSION_THRESHOLD` bytes (default 64 MB; 0 disables fusion =
one collective per tensor, matching `docs/tensor-fusion.md:18-28`).
`HVD_FUSION_MB` is the megabyte-denominated alias (fractions accepted;
the byte-exact reference variable wins when both are set) — see
`runtime.config.Config.refresh`.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.runtime.config import config


# XLA's backend collective-combiner passes re-merge independent
# all-reduces into one tuple all-reduce AFTER our bucketing (observed
# on the CPU backend: N independent bucket psums compile to a single
# tuple all-reduce scheduled after the whole backward — voiding the
# per-bucket overlap structure docs/scaling.md's model rests on).
# `xla_disable_hlo_passes` is the generic, per-compile escape hatch;
# unknown pass names are ignored, so one list covers every backend
# (verified on CPU: "cpu-all-reduce-combiner" is the pass that
# re-merges; "all-reduce-combiner" is the OSS/GPU/TPU pass name).
_COMBINER_PASSES = "all-reduce-combiner,cpu-all-reduce-combiner"

# None = not probed yet; set by the first combiner_override_options()
# call. Old jax/xla builds (observed: 0.4.37) cannot set
# xla_disable_hlo_passes through compiler_options at all — the binding
# drives protobuf reflection's SetString at a REPEATED field and every
# jit carrying the option crashes at compile time — so the override
# must be feature-probed, not assumed.
_COMBINER_OVERRIDE_OK: Optional[bool] = None


def _combiner_override_supported() -> bool:
    global _COMBINER_OVERRIDE_OK
    if _COMBINER_OVERRIDE_OK is None:
        try:
            # hvd: disable=HVD003(one-shot capability probe, cached in _COMBINER_OVERRIDE_OK for the process lifetime)
            jax.jit(lambda x: x + 0,
                    compiler_options={
                        "xla_disable_hlo_passes": _COMBINER_PASSES,
                    })(jnp.zeros(()))
            _COMBINER_OVERRIDE_OK = True
        # hvd: disable=HVD006(capability probe: any failure shape — TypeError, XlaRuntimeError, repeated-field crash — means no override; warned below)
        except Exception:  # noqa: BLE001 — any failure means no override
            import sys
            sys.stderr.write(
                "WARNING: this jax/xla build cannot disable the XLA "
                "collective-combiner passes (xla_disable_hlo_passes "
                "rejected); HOROVOD_FUSION_THRESHOLD buckets may be "
                "re-merged by the backend.\n")
            _COMBINER_OVERRIDE_OK = False
    return _COMBINER_OVERRIDE_OK


def combiner_override_options() -> dict:
    """jit `compiler_options` that pin HOROVOD_FUSION_THRESHOLD's
    bucket granularity through XLA's backend passes.

    The reference's fusion threshold controls collective granularity
    end to end (`mpi_ops.cc:1392-1419` merges *up to* the threshold,
    never past it); without this override the XLA backend combiner
    silently re-merges our buckets, so the env var's semantic — and
    the bucket-level backward/collective overlap — would stop at the
    IR. Returns {} when HOROVOD_XLA_COMBINER=xla (opt out: let XLA
    choose granularity) or when the build cannot express the override
    (degrade to XLA's granularity rather than crash every train
    step — see `_combiner_override_supported`).
    """
    if config.xla_combiner == "xla":
        return {}
    if not _combiner_override_supported():
        return {}
    return {"xla_disable_hlo_passes": _COMBINER_PASSES}


def _leaf_bytes(leaf) -> int:
    return int(np.prod(leaf.shape)) * leaf.dtype.itemsize if leaf.ndim else leaf.dtype.itemsize


def plan_buckets(leaves: List[Any],
                 threshold: Optional[int] = None) -> List[List[int]]:
    """Greedy same-dtype bucketing up to `threshold` bytes.

    Mirrors the coordinator's greedy merge of consecutive same-dtype
    allreduce responses under the fusion threshold
    (`mpi_ops.cc:1392-1419`). Returns a list of buckets, each a list of
    leaf indices. threshold<=0 disables fusion (singleton buckets).
    """
    if threshold is None:
        threshold = config.fusion_threshold
    if threshold <= 0:
        return [[i] for i in range(len(leaves))]
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        b = _leaf_bytes(leaf)
        if cur and (leaf.dtype != cur_dtype or cur_bytes + b > threshold):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
        cur_dtype = leaf.dtype
    if cur:
        buckets.append(cur)
    return buckets


def fused_allreduce_leaves(leaves: List[Any], *, axis_name: str,
                           average: bool = True,
                           threshold: Optional[int] = None,
                           reduce_dtype: Optional[Any] = None) -> List[Any]:
    """Allreduce a list of arrays with bucket fusion. Jittable; call
    inside shard_map with `axis_name` bound.

    reduce_dtype: optionally reduce in a different dtype (e.g. bf16) and
    cast back — a TPU-native bandwidth optimization (HOROVOD_ALLREDUCE_DTYPE).
    """
    buckets = plan_buckets(leaves, threshold)
    out: List[Any] = [None] * len(leaves)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            x = leaves[i]
            if reduce_dtype is not None and x.dtype != reduce_dtype:
                red = lax.psum(x.astype(reduce_dtype), axis_name).astype(x.dtype)
            else:
                red = lax.psum(x, axis_name)
            out[i] = red / lax.psum(1, axis_name) if average else red
            continue
        flat = jnp.concatenate([leaves[i].ravel() for i in bucket])
        if reduce_dtype is not None and flat.dtype != reduce_dtype:
            red = lax.psum(flat.astype(reduce_dtype), axis_name).astype(flat.dtype)
        else:
            red = lax.psum(flat, axis_name)
        if average:
            red = red / lax.psum(1, axis_name)
        offset = 0
        for i in bucket:
            n = int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
            out[i] = red[offset:offset + n].reshape(leaves[i].shape)
            offset += n
    return out


def fused_allreduce_tree(tree: Any, *, axis_name: str, average: bool = True,
                         threshold: Optional[int] = None,
                         reduce_dtype: Optional[Any] = None) -> Any:
    """Pytree version of `fused_allreduce_leaves` (gradients are pytrees)."""
    leaves, treedef = jax.tree.flatten(tree)
    reduced = fused_allreduce_leaves(
        leaves, axis_name=axis_name, average=average,
        threshold=threshold, reduce_dtype=reduce_dtype)
    return jax.tree.unflatten(treedef, reduced)
