"""SPMD collective kernels over a named mesh axis.

TPU-native data plane replacing the reference's collective execution engine
(`horovod/tensorflow/mpi_ops.cc:636-1146`, SURVEY C5): where the reference
memcpys tensors into a fusion buffer and calls `MPI_Allreduce` /
`ncclAllReduce` / `MPI_Allgatherv` / `MPI_Bcast` from a background thread,
these are pure jittable functions that lower to XLA `all-reduce`,
`all-gather` and `collective-permute` HLOs riding the ICI torus. They are
meant to be used inside `jax.shard_map` / `pjit` with the mesh axis bound;
the eager (outside-jit) API in `horovod_tpu/ops/eager.py` wraps them.

Reduction order note: XLA's all-reduce is deterministic for a fixed mesh,
unlike MPI where the algorithm may vary; correctness tests compare against
`tensor * size` with the same dtype thresholds as the reference
(`mpi_ops_test.py:96-100`).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.resilience import chaos


def _chaos_collective(op: str):
    """Chaos site ``collective_slow``: when armed, the calling host
    thread sleeps before dispatching `op` — the slow/hung-collective
    fault (a peer died, the rendezvous never completes). Host-side by
    design: under jit it fires at trace/dispatch time, which is
    exactly where a hung collective parks the controller in practice
    (the eager dispatch boundary in `ops/eager.py` carries the same
    site). Disabled ⇒ one global load + None check."""
    del op  # sites are engine-wide today; per-op filtering would key here
    chaos.slow_site("collective_slow")


def allreduce(x: jax.Array, *, average: bool = True,
              axis_name: str = "data") -> jax.Array:
    """Sum (or average) `x` over the mesh axis.

    Parity: `hvd.allreduce(tensor, average=)` dense path
    (`horovod/tensorflow/__init__.py:73-79`); the divide-by-size for
    `average=True` matches the reference exactly. Lowers to a single
    all-reduce HLO — bandwidth-optimal on the ICI ring by construction
    (the reference delegates the ring algorithm to NCCL/OpenMPI).
    Integer inputs with `average=True` floor-divide and keep their dtype,
    matching the reference's `tf.div` semantics.
    """
    _chaos_collective("allreduce")
    if not average:
        return lax.psum(x, axis_name)
    if jnp.issubdtype(x.dtype, jnp.integer):
        # Accumulate narrow ints in int32: both the sum and the divisor
        # would wrap in e.g. int8 beyond 127 ranks. (The reference only
        # admits int32/int64 to allreduce, mpi_ops.cc:1777.)
        acc = x.dtype if x.dtype.itemsize >= 4 else jnp.int32
        summed = lax.psum(x.astype(acc), axis_name)
        divisor = lax.psum(jnp.ones((), jnp.int32), axis_name)
        return (summed // divisor.astype(acc)).astype(x.dtype)
    return lax.pmean(x, axis_name)


def allgather(x: jax.Array, *, axis_name: str = "data") -> jax.Array:
    """Concatenate `x` from every rank along dim 0.

    Parity: `hvd.allgather` (`horovod/tensorflow/mpi_ops.py:151-167`) for
    the fixed-size case. SPMD programs have identical block shapes on every
    rank, so this is `lax.all_gather(..., tiled=True)`; the variable-dim-0
    semantics of `MPI_Allgatherv` (`mpi_ops.cc:732-809`) live in
    `allgatherv` below and in the eager path.
    """
    _chaos_collective("allgather")
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def allgatherv(x: jax.Array, valid_len: jax.Array, *, max_len: int,
               axis_name: str = "data") -> Tuple[jax.Array, jax.Array]:
    """Variable-dim-0 allgather under XLA's static shapes.

    TPU translation of `MPI_Allgatherv` (`mpi_ops.cc:785-806`): each rank
    holds `x` padded on dim 0 to `max_len` with `valid_len` (scalar int32)
    genuine rows. Returns `(gathered, sizes)` where `gathered` is
    `[world, max_len, ...]` stacked per-rank blocks and `sizes` is
    `[world]` int32 — the caller (eager path or model code) compacts the
    valid rows, mirroring the reference coordinator collecting per-rank
    dim-0 sizes into `MPIResponse.tensor_sizes` (`mpi_ops.cc:345-405`).
    """
    del max_len  # shape is already static; kept for API clarity
    gathered = lax.all_gather(x, axis_name, axis=0, tiled=False)
    sizes = lax.all_gather(valid_len.astype(jnp.int32), axis_name)
    return gathered, sizes.reshape(-1)


def broadcast(x: jax.Array, root_rank: int, *,
              axis_name: str = "data") -> jax.Array:
    """Every rank receives root_rank's value of `x`.

    Parity: `hvd.broadcast` (`horovod/tensorflow/mpi_ops.py:173-187`,
    kernel `mpi_ops.cc:1110-1137`). Implemented as a masked psum — only the
    root contributes — exact for every numeric dtype since exactly one
    rank is nonzero.

    Lowering (verified: `tests/test_collectives.py`
    TestBroadcastLowering pins it): ONE `all-reduce` HLO with the mask
    fused in — no all-gather, no loop. XLA has no rewrite of this
    pattern to `collective-broadcast`, so the wire cost is an
    all-reduce's ~2·|x|·(N−1)/N per link, ≈2x a perfect pipelined
    one-to-all. Accepted: in the Horovod model broadcast is the
    init-time weight sync (`BroadcastGlobalVariablesHook`, reference
    `horovod/tensorflow/__init__.py:143-166`), not a training-loop op,
    so one-shot cost beats the complexity of a chunked ppermute ring
    pipeline (the only way to reach 1x with today's JAX collectives).
    """
    _chaos_collective("broadcast")
    idx = lax.axis_index(axis_name)
    if jnp.issubdtype(x.dtype, jnp.bool_):
        masked = jnp.where(idx == root_rank, x, False)
        return lax.psum(masked.astype(jnp.int32), axis_name).astype(jnp.bool_)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def alltoall(x: jax.Array, *, axis_name: str = "data",
             split_axis: int = 0, concat_axis: int = 0) -> jax.Array:
    """All-to-all over the mesh axis (no reference equivalent; TPU-native
    extension used by Ulysses sequence parallelism, SURVEY §5.7)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reducescatter(x: jax.Array, *, average: bool = False,
                  axis_name: str = "data") -> jax.Array:
    """Reduce-scatter along dim 0 (TPU-native extension; later Horovod
    versions grew `hvd.reducescatter` — included for forward parity)."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if average:
        out = out / lax.psum(1, axis_name)
    return out


def my_rank(axis_name: str = "data") -> jax.Array:
    """Per-shard rank id inside shard_map (the SPMD analogue of
    `hvd.rank()` for code running *on* a rank)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str = "data") -> int:
    """Static size of a bound mesh axis (the SPMD `hvd.size()`);
    delegates to the single version-insulated implementation in
    `parallel.mesh`."""
    from horovod_tpu.parallel.mesh import axis_size as _axis_size
    return _axis_size(axis_name)
