"""Eager (outside-jit) collective API.

The reference's op-by-op surface: a TF-graph op per tensor
(`horovod/tensorflow/mpi_ops.py:132-190`) executed via the background
MPI thread. The TPU equivalent dispatches a tiny cached pjit'd program per
(op, name, shape, dtype) over the framework mesh — XLA's compile cache
plays the role of the reference's tensor table.

Input conventions (how Horovod's "each rank passes its local tensor" MPMD
call maps onto single-controller JAX):

* ``hvd.per_rank([t0, .., tN-1])`` / ``PerRank`` — explicit per-rank
  values; the true analogue of N MPI ranks each passing a different
  tensor. Used heavily by the test-suite (mirrors `mpi_ops_test.py`
  generating a different random tensor per rank).
* A plain array — the value every rank holds (replicated). Allreduce of a
  replicated value is `x * size` (sum) / `x` (average), matching what N
  identical MPI ranks would produce.
* In multi-controller mode (``hvdrun``), a plain array is *this process's
  local value* and the collective runs across processes.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops import collectives as C
from horovod_tpu.runtime import state as _state


@dataclasses.dataclass
class PerRank:
    """Explicit per-rank inputs for eager collectives (leading index =
    rank). Values may differ in dim 0 (variable allgather)."""
    values: List[Any]

    def __post_init__(self):
        self.values = [np.asarray(v) for v in self.values]


def per_rank(values: Sequence[Any]) -> PerRank:
    return PerRank(list(values))


def _normalize_name(name: str) -> str:
    """Parity with `mpi_ops.py:127-129`."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _auto_name(prefix: str, name: Optional[str], tensor,
               skip_dim0: bool = False, content_free: bool = False) -> str:
    """Stable auto-name keyed on op/shape/dtype, mirroring the reference's
    naming by tensor graph name (`mpi_ops.py:143-144`) — stable across
    steps so timeline pids and the stall table don't grow per call.
    skip_dim0: allgather inputs may legitimately differ in dim 0 across
    ranks, and negotiation keys on the name, so dim 0 stays out of it.
    content_free: multi-controller negotiation must produce the SAME name
    on processes that *disagree* on shape/dtype (that disagreement is what
    validation exists to catch), so auto-names there carry no tensor
    metadata — cross-process identity comes from call order, the same
    consistent-op-order contract Horovod itself requires."""
    if name is not None:
        return _normalize_name(name)
    if content_free:
        return prefix
    if isinstance(tensor, PerRank):
        v = tensor.values[0]
        shape, dtype = v.shape, v.dtype
    else:
        v = np.asarray(tensor) if not hasattr(tensor, "shape") else tensor
        shape, dtype = tuple(v.shape), v.dtype
    if skip_dim0:
        shape = ("v",) + tuple(shape[1:])
    dims = "x".join(map(str, shape)) or "scalar"
    return f"{prefix}_{dims}_{dtype}"


def _is_multicontroller(st) -> bool:
    return st.num_processes > 1


def _mc_negotiate(st, opname: str, op: str, arr: np.ndarray,
                  root_rank: Optional[int], allow_dim0: bool,
                  extra: Optional[str] = None,
                  timeout_s: Optional[float] = None):
    """Per-op metadata negotiation over the launcher's rendezvous server.

    The runtime equivalent of the reference's coordinator protocol
    (SURVEY §3.2 right half), with the reference's topology: every
    process posts its request (name/op/dtype/shape/root) once; process
    0 gathers all N, validates them — the checks `ConstructMPIResponse`
    runs on rank 0 (`mpi_ops.cc:266-474`) — and publishes ONE response
    that every other process reads (the coordinator's response
    broadcast, `mpi_ops.cc:1421-1427`). Non-coordinator traffic per op
    is therefore 2 round-trips (1 write + 1 read) independent of world
    size; the earlier all-read-all design cost N reads on each of N
    processes against one TCP server. Validation failures are published
    in the response so every process raises the same error instead of
    hanging. Returns the per-process metas.
    """
    import json
    from horovod_tpu.ops.validation import (CollectiveMismatchError,
                                            validate_requests)
    if st.native is None:
        raise RuntimeError("multi-process eager collectives require the "
                           "native control plane")
    if not st.native.ping():
        raise RuntimeError(
            "multi-process eager collectives require the rendezvous "
            "channel: this process is not connected to a coordinator. "
            "Launch with `hvdrun` (which sets HOROVOD_KV) or set "
            "HOROVOD_KV=host:port of a running rendezvous server.")
    seq = st.op_cache.setdefault("_mc_seq", {})
    cnt = seq.get(opname, 0)
    seq[opname] = cnt + 1
    meta = {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "op": op, "root": root_rank,
            "ndev": len(_mc_local_devices(st))}
    if extra is not None:
        # Caller-supplied descriptor validated for cross-rank equality
        # (e.g. grouped_allreduce's per-tensor boundaries, which the
        # flat payload's shape cannot express).
        meta["extra"] = extra
    # The coordinator consumes its own request from local memory; only
    # non-coordinator requests go over the wire.
    if st.process_rank != 0 and not st.native.kv_set(
            f"req/{opname}/{cnt}/{st.process_rank}",
            json.dumps(meta).encode()):
        raise RuntimeError(
            f"failed to post negotiation request for {opname} — "
            f"rendezvous connection lost")
    resp_key = f"resp/{opname}/{cnt}"

    if st.process_rank != 0:
        # The coordinator's sequential gather may legitimately take up
        # to N sequential per-peer waits when ranks arrive staggered,
        # so the response wait scales with world size.
        v = st.native.kv_get(resp_key,
                             timeout_ms=60000 * st.num_processes)
        if v is None:
            raise RuntimeError(
                f"negotiation timeout for {opname}: no response from "
                f"the coordinator (see stall warnings)")
        resp = json.loads(v.decode())
        if resp["status"] != "ok":
            if resp.get("kind") == "CollectiveMismatchError":
                raise CollectiveMismatchError(resp["error"])
            raise RuntimeError(resp["error"])
        return resp["metas"]

    # Coordinator: gather, validate, publish.
    def publish_error(exc):
        st.native.kv_set(resp_key, json.dumps(
            {"status": "error", "kind": type(exc).__name__,
             "error": str(exc)}).encode())

    import sys
    import time as _time
    from horovod_tpu.runtime.config import config as _config
    stall_s = max(1.0, float(_config.stall_warning_time))
    if timeout_s is None:
        timeout_s = 60.0 * st.num_processes
    deadline = _time.time() + timeout_s
    metas_by_rank = {st.process_rank: meta}  # own request: no round-trip
    pending = [r for r in range(st.num_processes)
               if r != st.process_rank]
    # Fast path: ONE blocking read per peer, preserving the
    # 2-round-trip-per-op negotiation count. The TOTAL fast-path
    # blocking is bounded by the stall threshold (not stall_s per
    # peer), so the first warning below fires on time even when
    # several peers are missing; laggards drop into the poll-and-warn
    # loop.
    t_fast = _time.time()
    for r in list(pending):
        budget = min(stall_s - (_time.time() - t_fast),
                     deadline - _time.time())
        if budget <= 0:
            break
        v = st.native.kv_get(f"req/{opname}/{cnt}/{r}",
                             timeout_ms=int(budget * 1000))
        if v is not None:
            metas_by_rank[r] = json.loads(v.decode())
            pending.remove(r)
    warned = False
    while pending:
        # NON-BLOCKING sweep BEFORE diagnosing (timeout_ms=0: the KV
        # server's wait_for(0) checks the predicate immediately): one
        # slow peer exhausting the shared fast-path budget must not get
        # healthy already-posted peers misreported as missing — and the
        # sweep must not itself delay the warning by 2s per dead peer.
        for r in list(pending):
            v = st.native.kv_get(f"req/{opname}/{cnt}/{r}",
                                 timeout_ms=0)
            if v is not None:
                metas_by_rank[r] = json.loads(v.decode())
                pending.remove(r)
        if not pending:
            break
        if not warned:
            # The reference's ready-ranks diagnostic
            # (CheckForStalledTensors, mpi_ops.cc:1150-1193): name the
            # stuck op AND which processes have/haven't posted its
            # request — the difference between "rank 3 died" and
            # "ranks disagree on op order" is exactly this list.
            sys.stderr.write(
                "WARNING: One or more tensors were submitted to be "
                "reduced, gathered or broadcasted by subset of ranks "
                "and are waiting for remainder of ranks for more than "
                "%d seconds. This may indicate that different ranks "
                "are trying to submit different tensors or that only "
                "subset of ranks is submitting tensors, which will "
                "cause deadlock.\nStalled op: %s "
                "[ready processes: %s, missing processes: %s]\n"
                % (int(stall_s), opname,
                   sorted(metas_by_rank), sorted(pending)))
            warned = True
        if _time.time() > deadline:
            exc = RuntimeError(
                f"negotiation timeout for {opname}: process(es) "
                f"{sorted(pending)} never submitted a request "
                f"(ready: {sorted(metas_by_rank)})")
            publish_error(exc)
            raise exc
        # Paced blocking poll between sweeps (bounded per peer so the
        # deadline check above stays roughly honest).
        for r in list(pending):
            v = st.native.kv_get(f"req/{opname}/{cnt}/{r}",
                                 timeout_ms=2000)
            if v is not None:
                metas_by_rank[r] = json.loads(v.decode())
                pending.remove(r)
    metas = [metas_by_rank[r] for r in range(st.num_processes)]
    # Uniform-ownership check on the *exchanged* counts: uneven device
    # ownership would make the duplication corrections in the mc
    # kernels silently wrong.
    ndevs = [m.get("ndev") for m in metas]
    if None not in ndevs and (
            len(set(ndevs)) > 1
            or ndevs[0] * st.num_processes != st.size):
        exc = RuntimeError(
            f"multi-process collectives require every process to own "
            f"the same number of devices; per-process counts {ndevs} "
            f"over world size {st.size}")
        publish_error(exc)
        raise exc
    try:
        validate_requests(
            name=opname, op=op,
            ops=[m["op"] for m in metas],
            dtypes=[m["dtype"] for m in metas],
            shapes=[tuple(m["shape"]) for m in metas],
            root_ranks=([m["root"] for m in metas]
                        if root_rank is not None else None),
            allow_dim0_mismatch=allow_dim0,
            native=st.native)
        extras = [m.get("extra") for m in metas]
        if any(e != extras[0] for e in extras):
            raise CollectiveMismatchError(
                f"Mismatched collective descriptor for {opname} "
                f"across ranks: {extras}")
    except Exception as exc:
        publish_error(exc)
        raise
    st.native.kv_set(resp_key, json.dumps(
        {"status": "ok", "metas": metas}).encode())
    return metas


def _mc_local_devices(st):
    import jax
    pidx = jax.process_index()
    return [d for d in st.devices if d.process_index == pidx]


def _mc_mesh2(st):
    """(proc, local) two-axis view of the multi-controller device set.

    Lets collectives reduce across PROCESSES on intra-process SHARDS:
    device (p, l) carries only chunk l of process p's block, the
    cross-process psum runs over ``proc`` in k parallel chunk groups,
    and the full result reassembles with an intra-process all_gather
    over ``local`` — so the wire payload per process is its block
    ONCE, not k times (VERDICT r2 next-#7). Cached on the state.
    """
    cached = getattr(st, "mc_mesh2", None)
    if cached is not None:
        return cached
    from jax.sharding import Mesh
    procs = sorted({d.process_index for d in st.devices})
    rows = [[d for d in st.devices if d.process_index == p]
            for p in procs]
    k = len(rows[0])
    if any(len(r) != k for r in rows):
        raise RuntimeError(
            f"multi-process collectives require uniform device "
            f"ownership; got {[len(r) for r in rows]}")
    mesh = Mesh(np.array(rows), ("proc", "local"))
    st.mc_mesh2 = mesh
    return mesh


def _mc_chunked_global(st, mesh2, x: np.ndarray):
    """Shard `x` (this process's block) over the ``local`` axis:
    [nproc, k, chunk] global array where device (p, l) holds the l-th
    flat chunk of process p's block — each local device receives 1/k
    of the block instead of a full copy."""
    import jax
    k = mesh2.shape["local"]
    n = x.size
    chunk = -(-n // k)
    flat = np.ravel(x)
    if chunk * k != n:
        flat = np.pad(flat, (0, chunk * k - n))
    blocks = flat.reshape(k, chunk)
    pidx = jax.process_index()
    # This process's row of the (proc, local) mesh — rows are ordered
    # by process index by construction in `_mc_mesh2`.
    procs = [r[0].process_index for r in mesh2.devices]
    row = mesh2.devices[procs.index(pidx)]
    sharding = NamedSharding(mesh2, P("proc", "local"))
    shards = [jax.device_put(jnp.asarray(blocks[l])[None, None], row[l])
              for l in range(k)]
    return jax.make_array_from_single_device_arrays(
        (mesh2.shape["proc"], k, chunk), sharding, shards), chunk


def _mc_global_array(st, local_block: np.ndarray) -> jax.Array:
    """Assemble the [world, ...] global array where every device owned by
    this process holds `local_block` as its shard."""
    local = _mc_local_devices(st)
    if len(local) * st.num_processes != st.size:
        # The k-duplication correction in mc allreduce (and the
        # one-block-per-process selection in mc allgather) assumes a
        # uniform device count per process; uneven ownership would give
        # silently wrong sums.
        raise RuntimeError(
            f"multi-process collectives require every process to own the "
            f"same number of devices; this process owns {len(local)} of "
            f"{st.size} across {st.num_processes} processes")
    sharding = NamedSharding(st.mesh, P(st.axis_name))
    shape = (st.size,) + local_block.shape
    block = jnp.asarray(local_block)[None]
    shards = [jax.device_put(block, d) for d in local]
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)




def _timeline(st, name, phase, activity=None):
    if st.timeline is not None:
        st.timeline.record(name, phase, activity)


def _validate_per_rank(st, name: str, op: str, vals: List[np.ndarray],
                       root_rank: Optional[int] = None,
                       allow_dim0_mismatch: bool = False) -> None:
    """Cross-rank metadata validation — the contract of the reference
    coordinator's `ConstructMPIResponse` (`mpi_ops.cc:266-474`): ranks must
    agree on dtype, shape (allgather: all dims but 0), and root rank.
    Delegates to the native control plane when available; raises the same
    error category (a precondition failure) the reference surfaces as
    `tf.errors.FailedPreconditionError` (`mpi_ops_test.py:284-356`).
    """
    from horovod_tpu.ops.validation import validate_requests
    validate_requests(
        name=name, op=op,
        dtypes=[str(v.dtype) for v in vals],
        shapes=[tuple(v.shape) for v in vals],
        root_ranks=None if root_rank is None else [root_rank] * len(vals),
        allow_dim0_mismatch=allow_dim0_mismatch,
        native=st.native,
    )


def _shard_over_mesh(st, stacked: np.ndarray) -> jax.Array:
    """Place a [world, ...] host array so shard i lives on device i."""
    sharding = NamedSharding(st.mesh, P(st.axis_name))
    return jax.device_put(jnp.asarray(stacked), sharding)


# Cached once: _run_collective runs per collective per step, and
# re-resolving the family through the registry lock every dispatch
# would put avoidable lock traffic on the eager hot path.
_COLLECTIVES_COUNTER = None


def _collectives_counter():
    global _COLLECTIVES_COUNTER
    if _COLLECTIVES_COUNTER is None:
        from horovod_tpu.obs import catalog as _obs_catalog
        _COLLECTIVES_COUNTER = _obs_catalog.collective_metrics()[
            "dispatched"]
    return _COLLECTIVES_COUNTER


# Same caching rule for the straggler tracker's module (the tracker
# itself may be swapped by tests — resolve it per dispatch, cheaply).
_STRAGGLER_MOD = None


def _straggler():
    global _STRAGGLER_MOD
    if _STRAGGLER_MOD is None:
        from horovod_tpu.obs import straggler
        _STRAGGLER_MOD = straggler
    return _STRAGGLER_MOD


def _run_collective(st, key, fn, data, *, mesh=None, in_specs=None,
                    out_specs=None):
    """Dispatch a cached shard_map'd collective over the framework mesh
    (or an explicit `mesh`/`in_specs`, e.g. the chunked mc (proc,
    local) mesh). Default `out_specs=P()` (replicated result);
    reducescatter/alltoall pass `P(axis)` because each device's result
    differs.

    `data` is either a host [world, ...] stack (single-controller) or an
    already-placed global jax.Array (multi-controller).
    """
    import time as _time

    from horovod_tpu.resilience import chaos
    # Straggler attribution (obs/straggler.py): per-dispatch host-side
    # enter/exit timestamps around the WHOLE dispatch — the chaos
    # slow-site delay, compile-cache misses and a blocked rendezvous
    # all land inside the bracket, which is exactly the per-rank skew
    # the fleet view attributes.
    t_enter = _time.time()
    # The slow/hung-collective fault at the eager dispatch boundary
    # (the traced twin in ops/collectives.py fires at trace time): the
    # host thread blocks exactly as it would waiting on a dead peer's
    # rendezvous, so StallMonitor brackets around this call see the op
    # pending.
    chaos.slow_site("collective_slow")
    # Observability: eager dispatches are the only collectives the
    # host can still see at runtime (SPMD in-graph ones compile away)
    # — count them by op so a scrape shows the eager-path volume.
    _collectives_counter().inc(op=key[0])
    jitted = st.op_cache.get(key)
    if jitted is None:
        # check_vma=False: all_gather outputs are replicated by
        # construction but JAX's static replication checker cannot prove
        # it, so the check is disabled for these dispatch wrappers.
        shaped = jax.shard_map(
            fn, mesh=st.mesh if mesh is None else mesh,
            in_specs=P(st.axis_name) if in_specs is None else in_specs,
            out_specs=P() if out_specs is None else out_specs,
            check_vma=False,
        )
        jitted = jax.jit(shaped)
        st.op_cache[key] = jitted
    if not isinstance(data, jax.Array):
        data = _shard_over_mesh(st, data)
    out = jitted(data)
    _straggler().tracker().record(key[0], _time.time() - t_enter)
    return out


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              _meta_extra: Optional[str] = None):
    """Eager allreduce. Parity: `horovod/tensorflow/__init__.py:43-79`
    (dense path) — sum over ranks, divided by size when `average`.

    Accepts a `PerRank`, a plain (replicated) array, or an
    `IndexedSlices` (sparse path: allgather of values+indices,
    `__init__.py:61-72`). `_meta_extra`: internal — an opaque
    descriptor validated for cross-rank equality during negotiation.
    """
    from horovod_tpu.ops.sparse import IndexedSlices, allreduce_indexed_slices
    st = _state.check_initialized()
    if isinstance(tensor, IndexedSlices):
        return allreduce_indexed_slices(tensor, average=average, name=name)
    opname = _auto_name("HorovodAllreduce", name, tensor,
                        content_free=_is_multicontroller(st))
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "allreduce", vals)
            stacked = np.stack(vals)
            _timeline(st, opname, "TOP_LEVEL", "ALLREDUCE")

            def _kernel(x):
                return C.allreduce(x[0], average=average,
                                   axis_name=st.axis_name)
            key = ("allreduce", average, stacked.shape, str(stacked.dtype))
            return _run_collective(st, key, _kernel, stacked)
        if _is_multicontroller(st):
            # True MPMD path: this process's local tensor, reduced
            # across processes after KV negotiation; ranks are
            # processes, matching Horovod's process-rank model. With
            # k > 1 local devices the block is SHARDED over them
            # (``local`` axis of `_mc_mesh2`), the cross-process psum
            # runs over ``proc`` in k parallel chunk groups, and an
            # intra-process all_gather reassembles — wire payload per
            # process is its block once (no k-fold duplication).
            x = np.asarray(tensor)
            _mc_negotiate(st, opname, "allreduce", x, None, False,
                          extra=_meta_extra)
            _timeline(st, opname, "TOP_LEVEL", "ALLREDUCE")
            nproc = st.num_processes
            k = st.size // nproc
            if k == 1 or x.size == 0:
                # One device per process: the plain mesh psum is
                # already payload-optimal.
                def _kernel(g):
                    from jax import lax
                    s = lax.psum(g[0], st.axis_name)
                    if jnp.issubdtype(s.dtype, jnp.integer):
                        return s // nproc if average else s
                    return s / nproc if average else s
                key = ("mc_allreduce", average, x.shape, str(x.dtype))
                return _run_collective(
                    st, key, _kernel, _mc_global_array(st, x))
            mesh2 = _mc_mesh2(st)
            garr, chunk = _mc_chunked_global(st, mesh2, x)
            n, shape = x.size, x.shape  # static in the cached kernel

            def _kernel(g):
                from jax import lax
                s = lax.psum(g, "proc")            # [1, 1, chunk]
                full = lax.all_gather(s, "local", axis=1,
                                      tiled=True)  # [1, k, chunk]
                flat = full.reshape(-1)[:n].reshape(shape)
                if jnp.issubdtype(flat.dtype, jnp.integer):
                    return flat // nproc if average else flat
                return flat / nproc if average else flat
            key = ("mc_allreduce2", average, x.shape, str(x.dtype))
            return _run_collective(st, key, _kernel, garr, mesh=mesh2,
                                   in_specs=P("proc", "local"))
        # Replicated value: every rank contributes the same tensor.
        x = jnp.asarray(tensor)
        _timeline(st, opname, "TOP_LEVEL", "ALLREDUCE")
        return x if average else x * st.size
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def allgather(tensor, name: Optional[str] = None):
    """Eager allgather, concatenating along dim 0; per-rank dim-0 sizes may
    differ (MPI_Allgatherv semantics, `mpi_ops.cc:732-809`). Under XLA's
    static shapes the variable case pads each rank's block to the max
    dim-0, gathers, then compacts — the size exchange the reference
    coordinator does in negotiation (`mpi_ops.cc:345-405`) is a psum'd
    size vector here.
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodAllgather", name, tensor, skip_dim0=True,
                        content_free=_is_multicontroller(st))
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "allgather", vals,
                               allow_dim0_mismatch=True)
            sizes = [v.shape[0] if v.ndim else 1 for v in vals]
            max_len = max(sizes)
            padded = []
            for v in vals:
                v2 = v.reshape((1,)) if v.ndim == 0 else v
                pad = [(0, max_len - v2.shape[0])] + [(0, 0)] * (v2.ndim - 1)
                padded.append(np.pad(v2, pad))
            stacked = np.stack(padded)
            _timeline(st, opname, "TOP_LEVEL", "ALLGATHER")
            if len(set(sizes)) == 1:
                def _kernel(x):
                    return C.allgather(x[0], axis_name=st.axis_name)
                key = ("allgather", stacked.shape, str(stacked.dtype))
                return _run_collective(st, key, _kernel, stacked)

            size_arr = np.asarray(sizes, np.int32)

            def _kernel(x):
                g, _ = C.allgatherv(
                    x[0], jnp.int32(0), max_len=max_len,
                    axis_name=st.axis_name)
                return g
            key = ("allgatherv", stacked.shape, str(stacked.dtype))
            gathered = _run_collective(st, key, _kernel, stacked)
            parts = [gathered[r, :size_arr[r]] for r in range(st.size)]
            return jnp.concatenate(parts, axis=0)
        if _is_multicontroller(st):
            x = np.asarray(tensor)
            x = x.reshape((1,)) if x.ndim == 0 else x
            metas = _mc_negotiate(st, opname, "allgather", x, None, True)
            _timeline(st, opname, "TOP_LEVEL", "ALLGATHER")
            # Variable dim-0: sizes came back in negotiation (the
            # reference's response.tensor_sizes, mpi_ops.cc:345-405).
            proc_sizes = [m["shape"][0] if m["shape"] else 1
                          for m in metas]
            max_len = max(proc_sizes)
            pad = [(0, max_len - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            padded = np.pad(x, pad)

            def _kernel(g):
                from jax import lax
                return lax.all_gather(g[0], st.axis_name, axis=0,
                                      tiled=False)
            key = ("mc_allgather", padded.shape, str(padded.dtype))
            gathered = _run_collective(
                st, key, _kernel, _mc_global_array(st, padded))
            # gathered: [world, max_len, ...]; devices of one process hold
            # identical copies, so select one representative row per
            # process ON DEVICE before the host transfer (avoids moving
            # the k-fold duplicate payload), then trim to true sizes.
            first_row = {}
            for i, d in enumerate(st.devices):
                first_row.setdefault(d.process_index, i)
            procs = sorted(first_row)
            picked = np.asarray(gathered[jnp.asarray(
                [first_row[p] for p in procs])])
            return jnp.concatenate(
                [picked[j, :proc_sizes[p]] for j, p in enumerate(procs)],
                axis=0)
        # Replicated value: result is size copies concatenated on dim 0.
        x = jnp.asarray(tensor)
        x2 = x.reshape((1,)) if x.ndim == 0 else x
        _timeline(st, opname, "TOP_LEVEL", "ALLGATHER")
        return jnp.concatenate([x2] * st.size, axis=0)
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Eager broadcast from `root_rank`. Parity:
    `horovod/tensorflow/mpi_ops.py:173-190` / kernel `mpi_ops.cc:1110-1137`.
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodBroadcast", name, tensor,
                        content_free=_is_multicontroller(st))
    if not (0 <= root_rank < st.size):
        raise ValueError(
            f"broadcast root_rank {root_rank} out of range for size {st.size}")
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "broadcast", vals,
                               root_rank=root_rank)
            stacked = np.stack(vals)
            _timeline(st, opname, "TOP_LEVEL", "BCAST")

            def _kernel(x):
                return C.broadcast(x[0], root_rank, axis_name=st.axis_name)
            key = ("broadcast", root_rank, stacked.shape, str(stacked.dtype))
            return _run_collective(st, key, _kernel, stacked)
        if _is_multicontroller(st):
            x = np.asarray(tensor)
            # root_rank is a process rank (Horovod semantics).
            if not (0 <= root_rank < st.num_processes):
                raise ValueError(
                    f"broadcast root_rank {root_rank} out of range for "
                    f"{st.num_processes} processes")
            _mc_negotiate(st, opname, "broadcast", x, root_rank, False)
            _timeline(st, opname, "TOP_LEVEL", "BCAST")
            root_dev = next(i for i, d in enumerate(st.devices)
                            if d.process_index == root_rank)

            def _kernel(g):
                return C.broadcast(g[0], root_dev, axis_name=st.axis_name)
            key = ("mc_broadcast", root_rank, x.shape, str(x.dtype))
            return _run_collective(
                st, key, _kernel, _mc_global_array(st, x))
        _timeline(st, opname, "TOP_LEVEL", "BCAST")
        return jnp.asarray(tensor)
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def _mc_positions(st):
    """Mesh-axis-position bookkeeping for the mc kernels. The mesh is
    built from `st.devices` in backend order, which is NOT guaranteed
    to group processes contiguously (the same reason `_mc_mesh2` and
    mc allgather map by `process_index` instead of assuming position
    `i` belongs to process `i // k`). Returns `(proc_of_pos,
    positions)`: the process rank owning each axis position, and each
    rank's positions in ascending order — rank being the index in the
    sorted `process_index` list, the convention all mc paths share."""
    procs = sorted({d.process_index for d in st.devices})
    rank_of = {p: i for i, p in enumerate(procs)}
    proc_of_pos = [rank_of[d.process_index] for d in st.devices]
    positions = [[] for _ in procs]
    for i, r in enumerate(proc_of_pos):
        positions[r].append(i)
    return proc_of_pos, positions


def alltoall(tensor, name: Optional[str] = None):
    """Eager all-to-all (TPU-native extension; later-Horovod
    `hvd.alltoall` forward parity): rank r receives the r-th dim-0 slice
    from every rank, concatenated.

    Accepts `PerRank` (returns all ranks' results stacked [world, ...]),
    a plain array in multi-controller mode (this process's block;
    returns THIS process's received tensor), or a plain replicated array
    in single-controller mode (returns the stacked [world, ...] results,
    consistent with `reducescatter`'s replicated convention).
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodAlltoall", name, tensor,
                        content_free=_is_multicontroller(st))
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "alltoall", vals)
            stacked = np.stack(vals)  # [world, world*chunk, ...]
            if stacked.shape[1] % st.size:
                raise ValueError(
                    f"alltoall dim 0 ({stacked.shape[1]}) must be "
                    f"divisible by world size {st.size}")
            _timeline(st, opname, "TOP_LEVEL", "ALLTOALL")

            def _kernel(x):
                return C.alltoall(x[0], axis_name=st.axis_name)

            out = _run_collective(
                st, ("alltoall", stacked.shape, str(stacked.dtype)),
                _kernel, stacked, out_specs=P(st.axis_name))
            # out concatenates per-device results on dim 0; re-stack so
            # out[r] is rank r's received tensor.
            return out.reshape((st.size,) + stacked.shape[1:])
        if _is_multicontroller(st):
            # True MPMD path: process p sends its q-th dim-0 slice to
            # process q. With k > 1 local devices (all holding the same
            # block), the exchange runs in k parallel one-device-per-
            # process groups — every device computes its process's full
            # result, no cross-group duplication on the wire per group.
            x = np.asarray(tensor)
            nproc = st.num_processes
            if x.shape[0] % nproc:
                raise ValueError(
                    f"alltoall dim 0 ({x.shape[0]}) must be divisible "
                    f"by the number of processes {nproc}")
            _mc_negotiate(st, opname, "alltoall", x, None, False)
            _timeline(st, opname, "TOP_LEVEL", "ALLTOALL")
            k = st.size // nproc
            # One device per process per group, at the devices' ACTUAL
            # mesh positions (no process-contiguity assumption); group
            # members in rank order, so member p receives slice p.
            _, positions = _mc_positions(st)
            groups = [[positions[p][j] for p in range(nproc)]
                      for j in range(k)]

            def _kernel(g):
                from jax import lax
                return lax.all_to_all(
                    g[0], st.axis_name, split_axis=0, concat_axis=0,
                    tiled=True, axis_index_groups=groups)

            out = _run_collective(
                st, ("mc_alltoall", x.shape, str(x.dtype)),
                _kernel, _mc_global_array(st, x),
                out_specs=P(st.axis_name))
            # Every local device holds this process's full result.
            return jnp.asarray(np.asarray(out.addressable_shards[0].data))
        # Replicated value: rank r receives slice r from every rank —
        # size copies of x's r-th slice; all ranks' results stacked.
        x = jnp.asarray(tensor)
        if x.shape[0] % st.size:
            raise ValueError(
                f"alltoall dim 0 ({x.shape[0]}) must be divisible by "
                f"world size {st.size}")
        _timeline(st, opname, "TOP_LEVEL", "ALLTOALL")
        s0 = x.shape[0] // st.size
        return jnp.stack([
            jnp.concatenate([x[r * s0:(r + 1) * s0]] * st.size, axis=0)
            for r in range(st.size)])
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def reducescatter(tensor, average: bool = False, name: Optional[str] = None):
    """Eager reduce-scatter (TPU-native extension; later-Horovod
    `hvd.reducescatter` forward parity): dim 0 is split across ranks
    after a sum.

    `PerRank` and single-controller replicated inputs return all ranks'
    shards stacked [world, ...]; a plain array in multi-controller mode
    is this process's local tensor and THIS process's shard of the
    cross-process reduction is returned (true MPMD semantics, matching
    `allreduce`'s plain-array convention).
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodReducescatter", name, tensor,
                        content_free=_is_multicontroller(st))
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "reducescatter", vals)
            stacked = np.stack(vals)
            if stacked.shape[1] % st.size:
                raise ValueError(
                    f"reducescatter dim 0 ({stacked.shape[1]}) must be "
                    f"divisible by world size {st.size}")
            _timeline(st, opname, "TOP_LEVEL", "REDUCESCATTER")

            def _kernel(x):
                return C.reducescatter(x[0], average=average,
                                       axis_name=st.axis_name)
            out = _run_collective(
                st, ("reducescatter", average, stacked.shape,
                     str(stacked.dtype)),
                _kernel, stacked, out_specs=P(st.axis_name))
            # out[r] is rank r's shard (dim0/world rows of the sum).
            shard0 = stacked.shape[1] // st.size
            return out.reshape((st.size, shard0) + stacked.shape[2:])
        if _is_multicontroller(st):
            # True MPMD path (VERDICT r3 next-#4): processes are the
            # ranks; every local device holds this process's block, so
            # the device-axis reduction counts each process k times and
            # the sum is corrected by /k (exact for integers too: every
            # term is duplicated exactly k-fold).
            x = np.asarray(tensor)
            nproc = st.num_processes
            if x.shape[0] % nproc:
                raise ValueError(
                    f"reducescatter dim 0 ({x.shape[0]}) must be "
                    f"divisible by the number of processes {nproc}")
            _mc_negotiate(st, opname, "reducescatter", x, None, False)
            _timeline(st, opname, "TOP_LEVEL", "REDUCESCATTER")
            k = st.size // nproc
            shard0 = x.shape[0] // nproc
            div = k * (nproc if average else 1)
            scatter_ok = x.shape[0] % st.size == 0
            proc_of_pos, positions = _mc_positions(st)

            if scatter_ok:
                # One psum_scatter over the device axis. With k > 1
                # local devices the block still crosses the wire k
                # times (each duplicate device participates); the
                # `_mc_mesh2` chunked scheme mc allreduce uses would
                # shave that and is the follow-up if eager
                # reducescatter ever becomes hot — eager ops pay a
                # host round-trip anyway.
                # psum_scatter hands chunk i to mesh POSITION i, and
                # positions are not process-contiguous in general, so
                # the summand's chunks are pre-permuted (sum commutes)
                # such that the device at position i receives chunk
                # `rank(i)*k + ordinal-of-i-within-its-rank` — i.e.
                # every process's devices end up holding exactly its
                # dim-0 shard, in ascending-position order.
                chunkrows = x.shape[0] // st.size
                desired = [0] * st.size
                for p, pos in enumerate(positions):
                    for j, i in enumerate(pos):
                        desired[i] = p * k + j
                perm = np.asarray(desired)

                def _kernel(g):
                    from jax import lax
                    xr = g[0].reshape((st.size, chunkrows)
                                      + x.shape[1:])
                    xp = xr[jnp.asarray(perm)].reshape(x.shape)
                    s = lax.psum_scatter(xp, st.axis_name,
                                         scatter_dimension=0, tiled=True)
                    if jnp.issubdtype(s.dtype, jnp.integer):
                        return s // div
                    return s / div
            else:
                # dim0 divides nproc but not nproc*k: full psum, then
                # each device slices its process's shard (rank looked
                # up from the device's actual mesh position).
                proc_arr = np.asarray(proc_of_pos)

                def _kernel(g):
                    from jax import lax
                    s = lax.psum(g[0], st.axis_name)
                    p = jnp.asarray(proc_arr)[
                        lax.axis_index(st.axis_name)]
                    sl = lax.dynamic_slice_in_dim(
                        s, p * shard0, shard0, 0)
                    if jnp.issubdtype(sl.dtype, jnp.integer):
                        return sl // div
                    return sl / div

            out = _run_collective(
                st, ("mc_reducescatter", average, scatter_ok, x.shape,
                     str(x.dtype)),
                _kernel, _mc_global_array(st, x),
                out_specs=P(st.axis_name))
            if scatter_ok:
                # This process's chunks, ascending mesh position =
                # ascending chunk index by the permutation above.
                shards = sorted(
                    out.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
                return jnp.asarray(np.concatenate(
                    [np.asarray(s.data) for s in shards], axis=0))
            # Fallback kernel: every local device holds the full shard.
            return jnp.asarray(np.asarray(
                out.addressable_shards[0].data))
        # Replicated value: consistent with the PerRank path — the
        # reduced tensor is x*size (or x when averaging), scattered
        # along dim 0.
        x = jnp.asarray(tensor)
        if x.shape[0] % st.size:
            raise ValueError(
                f"reducescatter dim 0 ({x.shape[0]}) must be divisible by "
                f"world size {st.size}")
        _timeline(st, opname, "TOP_LEVEL", "REDUCESCATTER")
        reduced = x if average else x * st.size
        return reduced.reshape(
            (st.size, x.shape[0] // st.size) + x.shape[1:])
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)
