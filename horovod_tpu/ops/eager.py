"""Eager (outside-jit) collective API.

The reference's op-by-op surface: a TF-graph op per tensor
(`horovod/tensorflow/mpi_ops.py:132-190`) executed via the background
MPI thread. The TPU equivalent dispatches a tiny cached pjit'd program per
(op, name, shape, dtype) over the framework mesh — XLA's compile cache
plays the role of the reference's tensor table.

Input conventions (how Horovod's "each rank passes its local tensor" MPMD
call maps onto single-controller JAX):

* ``hvd.per_rank([t0, .., tN-1])`` / ``PerRank`` — explicit per-rank
  values; the true analogue of N MPI ranks each passing a different
  tensor. Used heavily by the test-suite (mirrors `mpi_ops_test.py`
  generating a different random tensor per rank).
* A plain array — the value every rank holds (replicated). Allreduce of a
  replicated value is `x * size` (sum) / `x` (average), matching what N
  identical MPI ranks would produce.
* In multi-controller mode (``hvdrun``), a plain array is *this process's
  local value* and the collective runs across processes.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops import collectives as C
from horovod_tpu.runtime import state as _state


@dataclasses.dataclass
class PerRank:
    """Explicit per-rank inputs for eager collectives (leading index =
    rank). Values may differ in dim 0 (variable allgather)."""
    values: List[Any]

    def __post_init__(self):
        self.values = [np.asarray(v) for v in self.values]


def per_rank(values: Sequence[Any]) -> PerRank:
    return PerRank(list(values))


def _normalize_name(name: str) -> str:
    """Parity with `mpi_ops.py:127-129`."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _auto_name(prefix: str, name: Optional[str], tensor) -> str:
    """Stable auto-name keyed on op/shape/dtype, mirroring the reference's
    naming by tensor graph name (`mpi_ops.py:143-144`) — stable across
    steps so timeline pids and the stall table don't grow per call."""
    if name is not None:
        return _normalize_name(name)
    if isinstance(tensor, PerRank):
        v = tensor.values[0]
        shape, dtype = v.shape, v.dtype
    else:
        v = np.asarray(tensor) if not hasattr(tensor, "shape") else tensor
        shape, dtype = tuple(v.shape), v.dtype
    dims = "x".join(map(str, shape)) or "scalar"
    return f"{prefix}_{dims}_{dtype}"


def _check_multicontroller(st, op: str):
    """Multi-controller eager collectives land with the hvdrun launcher;
    until then fail loudly rather than silently skipping communication."""
    if st.num_processes > 1:
        raise NotImplementedError(
            f"eager {op} of a plain (non-per_rank) array across "
            f"{st.num_processes} processes requires the hvdrun "
            f"multi-controller path; wrap per-device values explicitly or "
            f"use the SPMD API inside shard_map.")


def _timeline(st, name, phase, activity=None):
    if st.timeline is not None:
        st.timeline.record(name, phase, activity)


def _validate_per_rank(st, name: str, op: str, vals: List[np.ndarray],
                       root_rank: Optional[int] = None,
                       allow_dim0_mismatch: bool = False) -> None:
    """Cross-rank metadata validation — the contract of the reference
    coordinator's `ConstructMPIResponse` (`mpi_ops.cc:266-474`): ranks must
    agree on dtype, shape (allgather: all dims but 0), and root rank.
    Delegates to the native control plane when available; raises the same
    error category (a precondition failure) the reference surfaces as
    `tf.errors.FailedPreconditionError` (`mpi_ops_test.py:284-356`).
    """
    from horovod_tpu.ops.validation import validate_requests
    validate_requests(
        name=name, op=op,
        dtypes=[str(v.dtype) for v in vals],
        shapes=[tuple(v.shape) for v in vals],
        root_ranks=None if root_rank is None else [root_rank] * len(vals),
        allow_dim0_mismatch=allow_dim0_mismatch,
        native=st.native,
    )


def _shard_over_mesh(st, stacked: np.ndarray) -> jax.Array:
    """Place a [world, ...] host array so shard i lives on device i."""
    sharding = NamedSharding(st.mesh, P(st.axis_name))
    return jax.device_put(jnp.asarray(stacked), sharding)


def _run_collective(st, key, fn, stacked):
    """Dispatch a cached shard_map'd collective over the framework mesh."""
    jitted = st.op_cache.get(key)
    if jitted is None:
        # check_vma=False: all_gather outputs are replicated by
        # construction but JAX's static replication checker cannot prove
        # it, so the check is disabled for these dispatch wrappers.
        shaped = jax.shard_map(
            fn, mesh=st.mesh,
            in_specs=P(st.axis_name),
            out_specs=P(),
            check_vma=False,
        )
        jitted = jax.jit(shaped)
        st.op_cache[key] = jitted
    return jitted(_shard_over_mesh(st, stacked))


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Eager allreduce. Parity: `horovod/tensorflow/__init__.py:43-79`
    (dense path) — sum over ranks, divided by size when `average`.

    Accepts a `PerRank`, a plain (replicated) array, or an
    `IndexedSlices` (sparse path: allgather of values+indices,
    `__init__.py:61-72`).
    """
    from horovod_tpu.ops.sparse import IndexedSlices, allreduce_indexed_slices
    st = _state.check_initialized()
    if isinstance(tensor, IndexedSlices):
        return allreduce_indexed_slices(tensor, average=average, name=name)
    opname = _auto_name("HorovodAllreduce", name, tensor)
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "allreduce", vals)
            stacked = np.stack(vals)
            _timeline(st, opname, "TOP_LEVEL", "ALLREDUCE")

            def _kernel(x):
                return C.allreduce(x[0], average=average,
                                   axis_name=st.axis_name)
            key = ("allreduce", average, stacked.shape, str(stacked.dtype))
            return _run_collective(st, key, _kernel, stacked)
        # Replicated value: every rank contributes the same tensor.
        _check_multicontroller(st, "allreduce")
        x = jnp.asarray(tensor)
        _timeline(st, opname, "TOP_LEVEL", "ALLREDUCE")
        return x if average else x * st.size
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def allgather(tensor, name: Optional[str] = None):
    """Eager allgather, concatenating along dim 0; per-rank dim-0 sizes may
    differ (MPI_Allgatherv semantics, `mpi_ops.cc:732-809`). Under XLA's
    static shapes the variable case pads each rank's block to the max
    dim-0, gathers, then compacts — the size exchange the reference
    coordinator does in negotiation (`mpi_ops.cc:345-405`) is a psum'd
    size vector here.
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodAllgather", name, tensor)
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "allgather", vals,
                               allow_dim0_mismatch=True)
            sizes = [v.shape[0] if v.ndim else 1 for v in vals]
            max_len = max(sizes)
            padded = []
            for v in vals:
                v2 = v.reshape((1,)) if v.ndim == 0 else v
                pad = [(0, max_len - v2.shape[0])] + [(0, 0)] * (v2.ndim - 1)
                padded.append(np.pad(v2, pad))
            stacked = np.stack(padded)
            _timeline(st, opname, "TOP_LEVEL", "ALLGATHER")
            if len(set(sizes)) == 1:
                def _kernel(x):
                    return C.allgather(x[0], axis_name=st.axis_name)
                key = ("allgather", stacked.shape, str(stacked.dtype))
                return _run_collective(st, key, _kernel, stacked)

            size_arr = np.asarray(sizes, np.int32)

            def _kernel(x):
                g, _ = C.allgatherv(
                    x[0], jnp.int32(0), max_len=max_len,
                    axis_name=st.axis_name)
                return g
            key = ("allgatherv", stacked.shape, str(stacked.dtype))
            gathered = _run_collective(st, key, _kernel, stacked)
            parts = [gathered[r, :size_arr[r]] for r in range(st.size)]
            return jnp.concatenate(parts, axis=0)
        # Replicated value: result is size copies concatenated on dim 0.
        _check_multicontroller(st, "allgather")
        x = jnp.asarray(tensor)
        x2 = x.reshape((1,)) if x.ndim == 0 else x
        _timeline(st, opname, "TOP_LEVEL", "ALLGATHER")
        return jnp.concatenate([x2] * st.size, axis=0)
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Eager broadcast from `root_rank`. Parity:
    `horovod/tensorflow/mpi_ops.py:173-190` / kernel `mpi_ops.cc:1110-1137`.
    """
    st = _state.check_initialized()
    opname = _auto_name("HorovodBroadcast", name, tensor)
    if not (0 <= root_rank < st.size):
        raise ValueError(
            f"broadcast root_rank {root_rank} out of range for size {st.size}")
    st.stall_monitor and st.stall_monitor.begin(opname)
    _timeline(st, opname, "NEGOTIATING")
    try:
        if isinstance(tensor, PerRank):
            vals = tensor.values
            if len(vals) != st.size:
                raise ValueError(
                    f"per_rank got {len(vals)} values for world size {st.size}")
            _validate_per_rank(st, opname, "broadcast", vals,
                               root_rank=root_rank)
            stacked = np.stack(vals)
            _timeline(st, opname, "TOP_LEVEL", "BCAST")

            def _kernel(x):
                return C.broadcast(x[0], root_rank, axis_name=st.axis_name)
            key = ("broadcast", root_rank, stacked.shape, str(stacked.dtype))
            return _run_collective(st, key, _kernel, stacked)
        _check_multicontroller(st, "broadcast")
        _timeline(st, opname, "TOP_LEVEL", "BCAST")
        return jnp.asarray(tensor)
    finally:
        _timeline(st, opname, "DONE")
        st.stall_monitor and st.stall_monitor.end(opname)


def alltoall(tensor, name: Optional[str] = None):
    """Eager all-to-all (TPU-native extension; later-Horovod
    `hvd.alltoall` forward parity): rank r receives the r-th dim-0 slice
    from every rank, concatenated."""
    st = _state.check_initialized()
    if isinstance(tensor, PerRank):
        vals = tensor.values
        if len(vals) != st.size:
            raise ValueError(
                f"per_rank got {len(vals)} values for world size {st.size}")
        stacked = np.stack(vals)  # [world, world*chunk, ...]

        def _kernel(x):
            return C.alltoall(x[0], axis_name=st.axis_name)

        sharding = NamedSharding(st.mesh, P(st.axis_name))
        shaped = jax.shard_map(_kernel, mesh=st.mesh,
                               in_specs=P(st.axis_name),
                               out_specs=P(st.axis_name),
                               check_vma=False)
        out = jax.jit(shaped)(jax.device_put(jnp.asarray(stacked), sharding))
        # out concatenates per-device results on dim 0; re-stack so
        # out[r] is rank r's received tensor.
        return out.reshape((st.size,) + stacked.shape[1:])
    raise TypeError("alltoall requires per_rank inputs")


def reducescatter(tensor, average: bool = False, name: Optional[str] = None):
    """Eager reduce-scatter (TPU-native extension): dim 0 is split across
    ranks after a sum; returns the per-rank shards stacked [world, ...]."""
    st = _state.check_initialized()
    if isinstance(tensor, PerRank):
        vals = tensor.values
        stacked = np.stack(vals)
        if stacked.shape[1] % st.size:
            raise ValueError(
                f"reducescatter dim 0 ({stacked.shape[1]}) must be "
                f"divisible by world size {st.size}")

        def _kernel(x):
            return C.reducescatter(x[0], average=average,
                                   axis_name=st.axis_name)
        shaped = jax.shard_map(_kernel, mesh=st.mesh,
                               in_specs=P(st.axis_name),
                               out_specs=P(st.axis_name),
                               check_vma=False)
        sharding = NamedSharding(st.mesh, P(st.axis_name))
        out = jax.jit(shaped)(
            jax.device_put(jnp.asarray(stacked), sharding))
        # out[r] is rank r's shard (dim0/world rows of the reduced sum).
        shard0 = stacked.shape[1] // st.size
        return out.reshape((st.size, shard0) + stacked.shape[2:])
    # Replicated value: consistent with the PerRank path — the reduced
    # tensor is x*size (or x when averaging), scattered along dim 0.
    _check_multicontroller(st, "reducescatter")
    x = jnp.asarray(tensor)
    if x.shape[0] % st.size:
        raise ValueError(
            f"reducescatter dim 0 ({x.shape[0]}) must be divisible by "
            f"world size {st.size}")
    reduced = x if average else x * st.size
    return reduced.reshape((st.size, x.shape[0] // st.size) + x.shape[1:])
