"""Cross-rank request validation.

The contract of the reference coordinator's `ConstructMPIResponse`
(`horovod/tensorflow/mpi_ops.cc:266-474`): before running a collective,
every rank's request for a given tensor name must agree on op type, dtype,
shape (allgather: every dim but 0) and root rank; disagreement fails the
op with a precondition error instead of hanging — the behavior the
reference's negative tests assert (`mpi_ops_test.py:284-356, 429-539`).

Under single-controller SPMD a disagreement cannot happen inside one traced
program, but the eager per-rank path and the multi-controller path can
disagree, so the check is real. When the native control plane is loaded the
check runs in C++ (`horovod_tpu/native/control_plane.cc`); this module is
the pure-Python fallback and the common entry point.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


class CollectiveMismatchError(ValueError):
    """Raised when ranks disagree on collective metadata.

    The TPU analogue of the reference surfacing
    `tf.errors.FailedPreconditionError` from coordinator validation.
    """


def validate_requests(name: str, op: str,
                      dtypes: Sequence[str],
                      shapes: Sequence[Tuple[int, ...]],
                      root_ranks: Optional[Sequence[int]] = None,
                      allow_dim0_mismatch: bool = False,
                      native=None,
                      ops: Optional[Sequence[str]] = None) -> None:
    # Op-type agreement (ConstructMPIResponse checks message_type across
    # ranks, mpi_ops.cc:290-300). Checked first — a broadcast-vs-allreduce
    # mix has per-rank root ranks of mixed None/int that the later checks
    # can't represent.
    if ops is not None:
        for r, o in enumerate(ops):
            if o != ops[0]:
                raise CollectiveMismatchError(
                    f"Mismatched collective operations: One or more ranks "
                    f"submitted tensor {name} as {o}, but rank 0 "
                    f"submitted it as {ops[0]}.")
    if native is not None:
        err = native.validate(name, op, list(dtypes), list(shapes),
                              list(root_ranks) if root_ranks else None,
                              allow_dim0_mismatch)
        if err:
            raise CollectiveMismatchError(err)
        return

    # Pure-Python fallback — same checks, same message shapes as
    # ConstructMPIResponse (mpi_ops.cc:290-340, 345-405, 409-430).
    first_dtype = dtypes[0]
    for r, dt in enumerate(dtypes):
        if dt != first_dtype:
            raise CollectiveMismatchError(
                f"Mismatched data types: One or more ranks submitted "
                f"tensor {name} with dtype {dt}, but rank 0 submitted "
                f"dtype {first_dtype}.")
    if root_ranks is not None:
        first_root = root_ranks[0]
        for r, rr in enumerate(root_ranks):
            if rr != first_root:
                raise CollectiveMismatchError(
                    f"Mismatched root ranks: One or more ranks submitted "
                    f"tensor {name} with root rank {rr}, but rank 0 "
                    f"submitted root rank {first_root}.")
    first_shape = shapes[0]
    for r, sh in enumerate(shapes):
        if len(sh) != len(first_shape):
            raise CollectiveMismatchError(
                f"Mismatched tensor ranks: tensor {name} has rank "
                f"{len(sh)} on rank {r} but {len(first_shape)} on rank 0.")
        start = 1 if allow_dim0_mismatch else 0
        if tuple(sh[start:]) != tuple(first_shape[start:]):
            what = ("non-first dimensions" if allow_dim0_mismatch
                    else "shapes")
            raise CollectiveMismatchError(
                f"Mismatched {what}: tensor {name} has shape {sh} on "
                f"rank {r} but {first_shape} on rank 0.")
