"""Weight-only int8 quantization for inference.

No reference equivalent — Horovod v0.10's inference story is a docs
recipe for stripping ops from a frozen graph; this is part of the
TPU-native inference extension. Decode is HBM-bound on weight and
KV-cache reads (every parameter is re-read once per generated token),
so storing the transformer block matmul kernels as int8 with
per-output-channel float scales halves their HBM traffic; the
`int8 -> bf16` dequant runs on-chip in VMEM, fused by XLA into the
consuming matmul's operand read inside the decode `lax.scan` body.

Scope: the Megatron block kernels — attention ``qkv``/``out``, the
gelu MLP's ``wi``/``wo``, and the SwiGLU MLP's ``gate``/``up``/
``down`` (LLaMA family) — ~80 % of a dense LM's parameters. Embedding table,
LM head (tied OR the separate untied ``lm_head``), and norms stay at
full precision: head-side quantization error lands directly on the
logits.

Flow: train (or load) a normal float tree, then

    qtree = quantize_lm_params(params)
    qmodel = TransformerLM(..., weight_quant="int8")
    out = qmodel.apply({"params": qtree}, tokens)

`TransformerLM(weight_quant="int8").init` creates the same tree
STRUCTURE (zero weights) — real values always come from
`quantize_lm_params`; init exists so flax shape/cache plumbing (and
`models.generate`'s decode clone) works unchanged.

Oracle (tests/test_quantization.py): the quantized model's outputs are
exactly the plain model's outputs on the dequantized tree — the only
approximation is the rounding in `quantize_int8`, which is bounded by
half a quantization step per element.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

# Module names whose 2-D "kernel" params are quantized — the Megatron
# block pair names used by ParallelSelfAttention / ParallelMLP /
# ParallelSwiGLU (the LLaMA-family MLP).
QUANT_KERNEL_MODULES = ("qkv", "out", "wi", "wo", "gate", "up", "down")


def quantize_int8(w: jax.Array, axis: int = 0
                  ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8 quantization of a matmul kernel.

    ``axis`` is the CONTRACTION axis (0 for the [in, out] kernels flax
    Dense uses): each output channel gets one scale, so dequantized
    columns are exact rescalings and the matmul's accumulation error
    stays per-channel-bounded. Returns ``(q int8, scale f32)`` with
    `w ≈ q * scale` and `|w - q·scale| <= scale/2` elementwise.
    All-zero channels get scale 1 (q = 0) to avoid 0/0.
    """
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0,
                      amax.astype(jnp.float32) / 127.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axis)


def dequantize_int8(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32, axis: int = 0) -> jax.Array:
    """`q * scale` back at ``dtype`` (scale re-expanded on ``axis``)."""
    return q.astype(dtype) * jnp.expand_dims(scale, axis).astype(dtype)


def _is_quant_site(path: Tuple[str, ...], leaf_dict: Any) -> bool:
    return (path and path[-1] in QUANT_KERNEL_MODULES
            and isinstance(leaf_dict, dict)
            and "kernel" in leaf_dict
            and getattr(leaf_dict["kernel"], "ndim", 0) == 2)


def quantize_lm_params(params: Any) -> Any:
    """Transform a float LM param tree into the structure
    `TransformerLM(weight_quant="int8")` consumes: each block-matmul
    ``kernel`` becomes ``kernel_q`` (int8) + ``kernel_scale`` (f32 per
    output channel); everything else passes through unchanged.

    Works on the UNSHARDED host tree: scales are computed over full
    contraction columns, so TP-sharding the result afterwards keeps
    every shard consistent with the same per-channel scale.
    """
    def walk(node, path):
        if not isinstance(node, dict):
            return node
        if _is_quant_site(path, node):
            q, scale = quantize_int8(node["kernel"], axis=0)
            out = {k: v for k, v in node.items() if k != "kernel"}
            out["kernel_q"] = q
            out["kernel_scale"] = scale
            return out
        return {k: walk(v, path + (k,)) for k, v in node.items()}

    return walk(params, ())


def dequantize_lm_params(qparams: Any, dtype=jnp.float32) -> Any:
    """Inverse structural transform (the oracle's reference path):
    rebuilds a plain float tree from a `quantize_lm_params` output."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        if "kernel_q" in node and "kernel_scale" in node:
            out = {k: v for k, v in node.items()
                   if k not in ("kernel_q", "kernel_scale")}
            out["kernel"] = dequantize_int8(
                node["kernel_q"], node["kernel_scale"], dtype)
            return out
        return {k: walk(v) for k, v in node.items()}

    return walk(qparams)
