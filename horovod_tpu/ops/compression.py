"""Gradient compression for the data-parallel allreduce.

Parity surface: the reference ships wire-dtype compression —
`hvd.DistributedOptimizer(compression=hvd.Compression.fp16)`
(`horovod/tensorflow/__init__.py:119-124, 152-158`: compress before the
allreduce, decompress after). Here the same knob is the reduce dtype of
the fused bucket path (`reduce_dtype=` / `HOROVOD_ALLREDUCE_DTYPE`,
`ops/fusion.py`), and `DistributedOptimizer(compression="fp16")` maps
onto it.

Beyond the reference: **PowerSGD** (Vogels et al., NeurIPS 2019) —
rank-r factorized gradient allreduce with error feedback, the standard
answer when the interconnect (the reference's own bandwidth-bound
VGG-16 case, `README.md:32`) rather than compute bounds scaling. Per
matrix-shaped gradient M [n, m] (leading dims folded), with a
persistent right factor Q [m, r]:

    M  = grad + error            (error feedback)
    P  = M @ Q        -> allreduce-mean            (r·n floats)
    P̂  = orthonormalize(P)       (thin QR)
    Q' = Mᵀ @ P̂       -> allreduce-mean            (r·m floats)
    approx = P̂ @ Q'ᵀ  ≈ rank-r( mean(M) )
    error  = M - approx          (carried to the next step)

Bytes on the wire drop from n·m to r·(n+m) per matrix; both
allreduces ride the SAME fused-bucket machinery as uncompressed
gradients (`allreduce_gradients`), so fusion thresholds, wire dtype,
and the SPMD/eager dispatch all apply unchanged. Non-matrix leaves
(1-D biases/norms), `IndexedSlices`, and matrices too small to win
(r·(n+m)·2 > n·m) go through the exact allreduce.

TPU notes: the per-leaf matmuls are shard-local MXU work; the QR is
[n, r] with r tiny (lax.linalg.qr, f32). All compression math runs in
f32 regardless of the gradient dtype (error feedback in low precision
destroys the convergence guarantee), outputs cast back.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = ["powersgd_allreduce", "PowerSGDState"]


class PowerSGDState(NamedTuple):
    """Per-leaf factor/error-feedback state, parallel to the flattened
    gradient leaves (None = leaf uses the exact allreduce path)."""
    qs: Any
    errs: Any
    # jax.random key the Qs were drawn from — kept so a state can be
    # re-initialized deterministically after a checkpoint restore.
    key: Any


def _matrix_view(p: jax.Array) -> jax.Array:
    """Fold leading dims: [d0, ..., dk, m] -> [n, m]."""
    return p.reshape(-1, p.shape[-1])


def _compressible(p: Any, rank: int) -> bool:
    from horovod_tpu.ops.sparse import IndexedSlices
    if isinstance(p, IndexedSlices) or getattr(p, "ndim", 0) < 2:
        return False
    if not jnp.issubdtype(p.dtype, jnp.floating):
        return False
    n = int(p.size) // int(p.shape[-1])
    m = int(p.shape[-1])
    # Compress only where the factorized payload wins by >= 2x.
    return rank * (n + m) * 2 <= n * m


def _orthonormalize(p: jax.Array) -> jax.Array:
    """Thin-QR orthonormal basis of P's columns (Vogels et al. use
    Gram-Schmidt; QR spans the same subspace and is one fused op)."""
    q, _ = jnp.linalg.qr(p)
    return q


def powersgd_allreduce(rank: int = 4, *,
                       axis_name: Optional[str] = None,
                       threshold: Optional[int] = None,
                       reduce_dtype: Optional[Any] = None,
                       seed: int = 17) -> optax.GradientTransformation:
    """Rank-``rank`` PowerSGD compress-allreduce as an optax transform.

    Chain it before an optimizer (or use
    ``hvd.DistributedOptimizer(tx, compression="powersgd")``): its
    `update` replaces each eligible gradient with the rank-r
    approximation of the cross-replica MEAN gradient and keeps the
    residual as error feedback; ineligible leaves are exact-allreduced.
    Outside any SPMD context (world size 1) the collectives are
    no-ops and the transform degrades to local rank-r projection +
    error feedback — same-step output != input, but the CUMULATIVE
    applied update converges to the true sum (the error-feedback
    contract, pinned by tests).
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")

    def init_fn(params):
        leaves = jax.tree.leaves(params)
        key = jax.random.PRNGKey(seed)
        qs, errs = [], []
        for idx, p in enumerate(leaves):
            if _compressible(p, rank):
                m2 = _matrix_view(p)
                qs.append(jax.random.normal(
                    jax.random.fold_in(key, idx),
                    (m2.shape[1], rank), jnp.float32))
                errs.append(jnp.zeros(m2.shape, jnp.float32))
            else:
                qs.append(None)
                errs.append(None)
        return PowerSGDState(qs=tuple(qs), errs=tuple(errs), key=key)

    def update_fn(updates, state, params=None):
        del params
        from horovod_tpu.jax import allreduce_gradients
        from horovod_tpu.ops.sparse import IndexedSlices
        leaves, treedef = jax.tree.flatten(
            updates, is_leaf=lambda x: isinstance(x, IndexedSlices))
        if len(leaves) != len(state.qs):
            raise ValueError(
                f"PowerSGD state holds {len(state.qs)} leaves but the "
                f"gradient tree has {len(leaves)} — init with the same "
                f"param tree the gradients come from")

        # Eligibility re-checked on the GRADIENT leaf, not just the
        # init-time param: a sparse IndexedSlices gradient (embedding
        # layers — models/word2vec.py emits them) or any shape/dtype
        # surprise at a compressible slot takes the exact path (its
        # error feedback stays frozen), never _matrix_view.
        def _still_ok(i):
            leaf = leaves[i]
            return (state.qs[i] is not None
                    and not isinstance(leaf, IndexedSlices)
                    and getattr(leaf, "ndim", 0) >= 2
                    and leaf.shape[-1] == state.qs[i].shape[0])

        comp = [i for i in range(len(leaves)) if _still_ok(i)]
        exact = [i for i in range(len(leaves)) if i not in set(comp)]

        # Exact path first (1-D, sparse, too-small): one fused pass.
        reduced = list(leaves)
        if exact:
            ex = allreduce_gradients(
                [leaves[i] for i in exact], axis_name=axis_name,
                average=True, threshold=threshold,
                reduce_dtype=reduce_dtype)
            for i, r in zip(exact, ex):
                reduced[i] = r

        new_qs = list(state.qs)
        new_errs = list(state.errs)
        if comp:
            ms = [_matrix_view(leaves[i]).astype(jnp.float32)
                  + state.errs[i] for i in comp]
            ps = [m @ state.qs[i] for m, i in zip(ms, comp)]
            ps = allreduce_gradients(
                ps, axis_name=axis_name, average=True,
                threshold=threshold, reduce_dtype=reduce_dtype)
            phats = [_orthonormalize(p) for p in ps]
            qs = [m.T @ ph for m, ph in zip(ms, phats)]
            qs = allreduce_gradients(
                qs, axis_name=axis_name, average=True,
                threshold=threshold, reduce_dtype=reduce_dtype)
            for m, ph, q, i in zip(ms, phats, qs, comp):
                approx = ph @ q.T
                new_errs[i] = m - approx
                new_qs[i] = q
                reduced[i] = approx.reshape(
                    leaves[i].shape).astype(leaves[i].dtype)

        return (jax.tree.unflatten(treedef, reduced),
                PowerSGDState(qs=tuple(new_qs), errs=tuple(new_errs),
                              key=state.key))

    return optax.GradientTransformation(init_fn, update_fn)
