"""Pallas TPU flash-attention kernel.

The hot op of the flagship transformer, written for the hardware: one
fused kernel per (batch, head, q-block) that streams K/V blocks through
VMEM with online-softmax accumulation in float32 scratch — the [Sq, Sk]
score matrix never touches HBM, Q·Kᵀ and P·V ride the MXU, and the
rescale/exp traffic stays on the VPU.

No reference equivalent: Horovod v0.10 contains no attention at all
(SURVEY §5.7); this is part of the TPU-native long-context extension.
The same math in plain-XLA form lives in
`horovod_tpu.parallel.sequence.blockwise_attention`, which is both the
correctness oracle for this kernel and its backward pass: the VJP
recomputes attention blockwise (flash-style recompute — O(S) memory,
no saved score matrix) and lets XLA differentiate the scan.

Layout is the framework-wide [batch, seq, heads, head_dim]; the kernel
internally works head-major. `ulysses_attention(attn_impl=
flash_attention)` composes this with sequence parallelism: all_to_all to
head-sharded layout, flash kernel locally, all_to_all back.

Grid iteration order puts the K/V-block dimension innermost (sequential
on TPU), so the float32 accumulators live in VMEM scratch across the
whole K sweep and results are written to HBM exactly once per q-block.
Fully-masked causal blocks are skipped (compute guarded by `pl.when`,
~2x step speedup for long causal sequences).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _compiler_params = lambda: pltpu.CompilerParams(  # noqa: E731
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None
    _compiler_params = lambda: None  # noqa: E731

NEG_INF = float("-inf")


def _band_j0(qi, *, window, q_offset, k_offset, block_q, block_k):
    """First k-block index that can intersect q-block ``qi``'s band —
    the banded grid's offset (shared by index_map and kernel so the
    DMA'd block and the in-kernel positions cannot disagree)."""
    lo = (q_offset + qi * block_q - (window - 1) - k_offset) // block_k
    return jnp.maximum(0, lo)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, window: "int | None",
                  banded: bool, nk_total: int,
                  q_offset: int, k_offset: int,
                  kv_len: int, block_q: int, block_k: int):
    """One (batch, head, q-block, k-block) grid cell.

    ``banded``: the innermost grid axis runs over only the k-blocks
    that can intersect the sliding-window band of this q-block
    (index_map adds `_band_j0`); out-of-range logical blocks (clamped
    duplicates at the sequence end) are skipped by the validity guard.

    Scratch (persistent across the innermost k-block sweep):
      acc_ref [block_q, D] f32 — unnormalized output accumulator
      m_ref   [block_q, 128] f32 — running row max (lane-replicated)
      l_ref   [block_q, 128] f32 — running softmax denominator
    """
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Global positions of this block's rows/cols (for causal + pad masks).
    q_start = q_offset + qi * block_q
    if banded:
        jl = _band_j0(qi, window=window, q_offset=q_offset,
                      k_offset=k_offset, block_q=block_q,
                      block_k=block_k) + ki
        jc = jnp.minimum(jl, nk_total - 1)   # what the index_map DMA'd
        in_range = jl <= nk_total - 1
    else:
        jl = jc = ki
        in_range = True
    k_start = k_offset + jc * block_k

    def _block():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]

        mask = None
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = rows >= cols
            if window is not None:
                # Sliding window: same band rule as
                # sequence.banded_causal_mask, global positions.
                mask = jnp.logical_and(mask, rows - cols < window)
        if kv_len % block_k:
            # Zero-padding tail of the key axis (local index >= kv_len);
            # trivially all-true except in the last k block.
            local = jc * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            pad_ok = local < kv_len
            mask = pad_ok if mask is None else jnp.logical_and(mask, pad_ok)
        if mask is not None:
            logits = jnp.where(mask, logits, NEG_INF)

        m_prev = m_ref[...]                                  # [bq, 128]
        l_prev = l_ref[...]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)      # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)                   # [bq, 128]
        # Rows with every key masked so far keep m == -inf; shift by 0
        # there so exp(-inf - 0) = 0 instead of exp(-inf - -inf) = NaN.
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - shift[:, :1])                   # [bq, bk]
        corr = jnp.where(m_prev == NEG_INF, 0.0,
                         jnp.exp(m_prev - shift))            # [bq, 128]
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)                  # [bk, D]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, D]
        acc_ref[...] = acc_ref[...] * corr[:, :1] + pv
        m_ref[...] = m_new

    if causal:
        # Skip blocks entirely in the future: the earliest key in the
        # block is later than the latest query row. With a window,
        # also skip blocks entirely in the past (the newest key older
        # than the oldest query's window start) and clamped duplicates
        # past the banded grid's end.
        relevant = k_start <= q_start + block_q - 1
        if window is not None:
            relevant = jnp.logical_and(
                relevant,
                k_start + block_k - 1 >= q_start - window + 1)
        if banded:
            relevant = jnp.logical_and(relevant, in_range)
        pl.when(relevant)(_block)
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[...][:, :1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal, window, q_offset, k_offset,
                   block_q, block_k, interpret):
    """[B, S, H, D] flash attention forward via pallas_call."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    bq = min(block_q, max(Sq, 1))
    bk = min(block_k, max(Sk, 1))
    nq = -(-Sq // bq)
    nk = -(-Sk // bk)

    # Head-major layout for the kernel; XLA fuses the transposes.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if nq * bq != Sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, nq * bq - Sq), (0, 0)))
    if nk * bk != Sk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, nk * bk - Sk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, nk * bk - Sk), (0, 0)))

    # Sliding window: shrink the innermost grid to the k-blocks that
    # can intersect each q-block's band — out-of-band K/V blocks are
    # never DMA'd at all, so a long-context SWA step moves
    # O(S·(window+block)) bytes instead of O(S²).
    banded = causal and window is not None
    if banded:
        span = bq + window - 1                 # key span of one q-block
        nkb = min(nk, -(-span // bk) + 1)

        def k_map(b, h, i, j):
            j0 = _band_j0(i, window=window, q_offset=q_offset,
                          k_offset=k_offset, block_q=bq, block_k=bk)
            return (b, h, jnp.minimum(j0 + j, nk - 1), 0)
    else:
        nkb = nk

        def k_map(b, h, i, j):
            return (b, h, j, 0)

    kernel = functools.partial(
        _flash_kernel, scale=D ** -0.5, causal=causal, window=window,
        banded=banded, nk_total=nk,
        q_offset=q_offset, k_offset=k_offset, kv_len=Sk,
        block_q=bq, block_k=bk)

    grid = (B, H, nq, nkb)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), k_map),
            pl.BlockSpec((1, 1, bk, D), k_map),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, nq * bq, D), q.dtype),
        scratch_shapes=[
            _scratch((bq, D), jnp.float32),
            _scratch((bq, 128), jnp.float32),
            _scratch((bq, 128), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(),
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :, :Sq, :]
    return jnp.transpose(out, (0, 2, 1, 3))


def _scratch(shape, dtype):
    if _VMEM is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable")
    return _VMEM(shape, dtype)


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _make_flash(causal, window, q_offset, k_offset, block_q, block_k,
                interpret):
    """Config-specialized flash fn with a recompute VJP.

    Backward = flash-style recompute: differentiate the blockwise
    online-softmax scan (`sequence.blockwise_attention`, the same math)
    instead of saving the score matrix — O(S) residual memory, the
    standard TPU rematerialization trade. With a sliding window the
    backward is BANDED like the forward (`_banded_bwd`): Q is scanned
    in `block_q` chunks and each chunk's VJP sees only the
    `block_q + window - 1` keys its band can touch, so SWA training
    moves O(S·(window+block)) bytes/FLOPs end to end, not O(S²).
    """
    from horovod_tpu.parallel.sequence import blockwise_attention

    def ref(q, k, v):
        return blockwise_attention(
            q, k, v, block_size=block_k, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset)

    def _banded_bwd(q, k, v, g):
        B, Sq, H, D = q.shape
        Sk = k.shape[1]
        C = min(block_q, Sq)
        span = C + window - 1          # keys one q-chunk's band touches
        nc = -(-Sq // C)
        pad_q = nc * C - Sq
        if pad_q:
            # Padded q rows sit past the real sequence; their cotangent
            # rows are zero, so every gradient contribution they make
            # vanishes (dq row-local; dk/dv weighted by g rows).
            q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
            g = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0), (0, 0)))

        def body(carry, ci):
            dq_a, dk_a, dv_a = carry
            qc = jax.lax.dynamic_slice_in_dim(q, ci * C, C, axis=1)
            gc = jax.lax.dynamic_slice_in_dim(g, ci * C, C, axis=1)
            # First key the chunk's band can touch, clamped so the
            # static-size slice stays in range; the k_offset handed to
            # the ref keeps masking exact under the clamp (keys pulled
            # into the slice but outside the band are masked out).
            lo = q_offset + ci * C - (window - 1) - k_offset
            start = jnp.clip(lo, 0, Sk - span)
            kc = jax.lax.dynamic_slice_in_dim(k, start, span, axis=1)
            vc = jax.lax.dynamic_slice_in_dim(v, start, span, axis=1)
            fn = functools.partial(
                blockwise_attention, block_size=block_k, causal=True,
                window=window, q_offset=q_offset + ci * C,
                k_offset=k_offset + start)
            _, vjp = jax.vjp(fn, qc, kc, vc)
            dqc, dkc, dvc = vjp(gc)
            dq_a = jax.lax.dynamic_update_slice_in_dim(
                dq_a, dqc.astype(jnp.float32), ci * C, axis=1)
            # Adjacent bands overlap by window-1 keys: read-add-write.
            dk_a = jax.lax.dynamic_update_slice_in_dim(
                dk_a, jax.lax.dynamic_slice_in_dim(dk_a, start, span, 1)
                + dkc.astype(jnp.float32), start, axis=1)
            dv_a = jax.lax.dynamic_update_slice_in_dim(
                dv_a, jax.lax.dynamic_slice_in_dim(dv_a, start, span, 1)
                + dvc.astype(jnp.float32), start, axis=1)
            return (dq_a, dk_a, dv_a), None

        z = (jnp.zeros(q.shape, jnp.float32),
             jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32))
        (dq, dk, dv), _ = jax.lax.scan(body, z, jnp.arange(nc))
        return (dq[:, :Sq].astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    @jax.custom_vjp
    def flash(q, k, v):
        return _flash_forward(
            q, k, v, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset,
            block_q=block_q, block_k=block_k, interpret=interpret)

    def fwd(q, k, v):
        return flash(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        # Band the backward only when it actually shrinks the key span.
        if (causal and window is not None
                and min(block_q, q.shape[1]) + window - 1 < k.shape[1]):
            return _banded_bwd(q, k, v, g)
        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask=None, *, causal: bool = False,
                    window: Optional[int] = None,
                    q_offset: int = 0, k_offset: int = 0,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused flash attention, [B, S, H, D] → [B, S, H, D].

    Args:
      q, k, v: [batch, seq, heads, head_dim] (any float dtype; compute is
        float32, output matches `q.dtype`). `head_dim` a multiple of 128
        keeps the MXU fully tiled; smaller values work but underfill lanes.
      mask: unsupported here (only `causal=`); pass explicit masks to
        `parallel.tensor.dot_product_attention`. Accepted positionally as
        None so the fn is drop-in for `ParallelSelfAttention.attn_fn`.
      causal: apply a causal mask using global positions
        `q_offset + i >= k_offset + j` (offsets support ring-attention
        style rotated blocks).
      window: sliding-window attention (last `window` positions only;
        requires causal; >= 1). Banded end to end: the FORWARD's
        innermost grid axis covers only the k-blocks intersecting each
        q-block's band (out-of-band K/V never read from HBM), and the
        recompute BACKWARD scans q in `block_q` chunks whose VJPs see
        only each band's `block_q + window - 1` keys — so an SWA
        training step moves O(S·(window+block)) bytes and FLOPs, not
        O(S²).
      block_q, block_k: VMEM tile sizes (128 matches the MXU; raise
        block_k to 256/512 when head_dim is small).
      interpret: run the kernel in interpreter mode (None = auto: True
        off-TPU, so the same tests run on the CPU mesh).
    """
    if mask is not None:
        raise NotImplementedError(
            "flash_attention supports causal masking only; use "
            "dot_product_attention for arbitrary masks")
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    from horovod_tpu.parallel.sequence import check_window
    check_window(window)
    if interpret is None:
        interpret = _auto_interpret()
    fn = _make_flash(bool(causal),
                     None if window is None else int(window),
                     int(q_offset), int(k_offset),
                     int(block_q), int(block_k), bool(interpret))
    return fn(q, k, v)
