"""Pallas TPU flash-attention kernel.

The hot op of the flagship transformer, written for the hardware: one
fused kernel per (batch, head, q-block) that streams K/V blocks through
VMEM with online-softmax accumulation in float32 scratch — the [Sq, Sk]
score matrix never touches HBM, Q·Kᵀ and P·V ride the MXU, and the
rescale/exp traffic stays on the VPU.

No reference equivalent: Horovod v0.10 contains no attention at all
(SURVEY §5.7); this is part of the TPU-native long-context extension.
The backward is fused Pallas too (FlashAttention-2 style, the
default): the forward saves only the row logsumexp, and two kernels
rebuild each probability tile on the fly for dK/dV and dQ — O(S)
residual memory, no scan-residual HBM traffic; under a sliding window
both backward sweeps are banded like the forward grid. The same math
in plain-XLA form lives in
`horovod_tpu.parallel.sequence.blockwise_attention`, the correctness
oracle for both directions and the recompute-VJP fallback
(HOROVOD_FLASH_BWD=recompute; banded for sliding-window training).

Layout is the framework-wide [batch, seq, heads, head_dim]; the kernel
internally works head-major. `ulysses_attention(attn_impl=
flash_attention)` composes this with sequence parallelism: all_to_all to
head-sharded layout, flash kernel locally, all_to_all back.

Grid iteration order puts the K/V-block dimension innermost (sequential
on TPU), so the float32 accumulators live in VMEM scratch across the
whole K sweep and results are written to HBM exactly once per q-block.
Fully-masked causal blocks are skipped (compute guarded by `pl.when`,
~2x step speedup for long causal sequences).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _compiler_params = lambda: pltpu.CompilerParams(  # noqa: E731
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))
except (ImportError, AttributeError):  # pragma: no cover
    # ImportError: no pallas TPU backend in this build; AttributeError:
    # a build old enough to lack VMEM/CompilerParams. Anything else is
    # a real bug and must surface.
    pltpu = None
    _VMEM = None
    _compiler_params = lambda: None  # noqa: E731

NEG_INF = float("-inf")

# The row-logsumexp rides between the fwd and bwd kernels lane-
# replicated to a full 128-lane trailing dim: real Mosaic requires the
# last two block dims to be (8k, 128m) or equal to the array dims, so a
# rank-3 [B, H, S] lse with (1, 1, bq) blocks is UNLOWERABLE on
# hardware (it only ever worked in interpret mode); and after (8, 128)
# tile padding a narrower trailing dim would occupy the same HBM
# anyway. Kernel-internal only — the public API still returns [B,H,S].
LSE_LANES = 128


def _snap_tile(block: int, S: int) -> int:
    """Largest hardware-legal tile <= ``block`` for a length-``S``
    grid axis. Real Mosaic (v5e/v5-lite captured it first —
    BENCH_builder_r04's block-shape-divisibility failure) requires
    the second-minor block dim to be a multiple of 8 OR equal to the
    array dim: a single block equal to the (padded) axis always
    qualifies, a multi-block tile must be 8-aligned — so a
    user-swept tile like 100 snaps to 96 instead of tracing a kernel
    only interpret mode can run (the r4 lesson: interpret accepts
    shapes real Mosaic rejects). Shared by the forward and both
    backward grids so their tiles can never disagree."""
    b = min(block, max(S, 1))
    if b >= S:
        return b           # one block == the padded axis: always legal
    return max(8, b - b % 8)


def mosaic_block_ok(block_shape, array_shape) -> bool:
    """The v5-lite lowering rule for one (block, array) pair: the
    last two block dims must be multiples of (8, 128) respectively,
    or equal to the corresponding array dims. Introspection for
    `flash_tile_check` and the CPU regression tests — verifiable
    without a TPU window."""
    (b2, b1), (a2, a1) = block_shape[-2:], array_shape[-2:]
    return ((b1 % 128 == 0 or b1 == a1)
            and (b2 % 8 == 0 or b2 == a2))


def flash_tile_check(Sq: int, Sk: int, H: int, Hkv: int, D: int, *,
                     block_q: int = 128, block_k: int = 128):
    """Every (name, block shape, array shape, legal) the fwd + bwd
    pallas_calls will use at these shapes after tile snapping — the
    static half of the v5e regression test: a config is
    hardware-lowerable iff every entry's ``legal`` bit is True, and
    that is checkable on CPU (interpret mode would happily run
    illegal tiles, which is exactly how the r04 failure shipped)."""
    bq = _snap_tile(block_q, Sq)
    bk = _snap_tile(block_k, Sk)
    nq = -(-Sq // bq)
    nk = -(-Sk // bk)
    B = 1   # batch rides a leading grid dim, never a constrained one
    entries = [
        ("fwd.q", (1, 1, bq, D), (B, H, nq * bq, D)),
        ("fwd.kv", (1, 1, bk, D), (B, Hkv, nk * bk, D)),
        ("fwd.out", (1, 1, bq, D), (B, H, nq * bq, D)),
        ("fwd.lse", (1, 1, bq, LSE_LANES), (B, H, nq * bq, LSE_LANES)),
        ("bwd.dq.q", (1, 1, bq, D), (B, H, nq * bq, D)),
        ("bwd.dq.lse", (1, 1, bq, LSE_LANES),
         (B, H, nq * bq, LSE_LANES)),
        ("bwd.dq.kv", (1, 1, bk, D), (B, Hkv, nk * bk, D)),
        ("bwd.dkv.q", (1, 1, bq, D), (B, H, nq * bq, D)),
        ("bwd.dkv.out", (1, 1, bk, D), (B, Hkv, nk * bk, D)),
    ]
    return [(name, blk, arr, mosaic_block_ok(blk, arr))
            for name, blk, arr in entries]


def _band_j0(qi, *, window, q_offset, k_offset, block_q, block_k):
    """First k-block index that can intersect q-block ``qi``'s band —
    the banded grid's offset (shared by index_map and kernel so the
    DMA'd block and the in-kernel positions cannot disagree)."""
    lo = (q_offset + qi * block_q - (window - 1) - k_offset) // block_k
    return jnp.maximum(0, lo)


def _band_i0(j, *, q_offset, k_offset, block_q, block_k):
    """First q-block index whose rows can see k-block ``j`` under the
    causal band (q >= k) — the dK/dV banded grid's offset."""
    lo = (k_offset + j * block_k - q_offset) // block_q
    return jnp.maximum(0, lo)


def _mask_block(q_start, k_start, *, causal, window, kv_len, k_local0,
                block_q, block_k):
    """The fwd/bwd-shared mask for one [block_q, block_k] tile, or None.

    `q_start`/`k_start` are GLOBAL positions (offset-aware, the
    `banded_causal_mask` band rule); `k_local0` is the block's LOCAL
    key index origin for the zero-pad tail test.
    """
    mask = None
    if causal:
        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = rows >= cols
        if window is not None:
            mask = jnp.logical_and(mask, rows - cols < window)
    if kv_len % block_k:
        local = k_local0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        pad_ok = local < kv_len
        mask = pad_ok if mask is None else jnp.logical_and(mask, pad_ok)
    return mask


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, window: "int | None",
                  banded: bool, nk_total: int,
                  q_offset: int, k_offset: int,
                  kv_len: int, block_q: int, block_k: int):
    """One (batch, head, q-block, k-block) grid cell.

    ``banded``: the innermost grid axis runs over only the k-blocks
    that can intersect the sliding-window band of this q-block
    (index_map adds `_band_j0`); out-of-range logical blocks (clamped
    duplicates at the sequence end) are skipped by the validity guard.

    Scratch (persistent across the innermost k-block sweep):
      acc_ref [block_q, D] f32 — unnormalized output accumulator
      m_ref   [block_q, 128] f32 — running row max (lane-replicated)
      l_ref   [block_q, 128] f32 — running softmax denominator
    """
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Global positions of this block's rows/cols (for causal + pad masks).
    q_start = q_offset + qi * block_q
    if banded:
        jl = _band_j0(qi, window=window, q_offset=q_offset,
                      k_offset=k_offset, block_q=block_q,
                      block_k=block_k) + ki
        jc = jnp.minimum(jl, nk_total - 1)   # what the index_map DMA'd
        in_range = jl <= nk_total - 1
    else:
        jl = jc = ki
        in_range = True
    k_start = k_offset + jc * block_k

    def _block():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]

        mask = _mask_block(q_start, k_start, causal=causal,
                           window=window, kv_len=kv_len,
                           k_local0=jc * block_k,
                           block_q=block_q, block_k=block_k)
        if mask is not None:
            logits = jnp.where(mask, logits, NEG_INF)

        m_prev = m_ref[...]                                  # [bq, 128]
        l_prev = l_ref[...]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)      # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)                   # [bq, 128]
        # Rows with every key masked so far keep m == -inf; shift by 0
        # there so exp(-inf - 0) = 0 instead of exp(-inf - -inf) = NaN.
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - shift[:, :1])                   # [bq, bk]
        corr = jnp.where(m_prev == NEG_INF, 0.0,
                         jnp.exp(m_prev - shift))            # [bq, 128]
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)                  # [bk, D]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, D]
        acc_ref[...] = acc_ref[...] * corr[:, :1] + pv
        m_ref[...] = m_new

    # Skip blocks entirely outside the causal band (future keys, or —
    # with a window — keys entirely in the past) and clamped
    # duplicates past the banded grid's end. Non-causal keeps a traced
    # trivially-true guard ("block intersects real keys"): an
    # UNGUARDED body trips a varying-manual-axes mismatch inside the
    # pallas interpreter under shard_map(check_vma=True).
    rel = _relevant_block(q_start, k_start, causal=causal,
                          window=window, block_q=block_q,
                          block_k=block_k)
    if rel is None:
        rel = jnp.asarray(jc) * block_k < kv_len
    if banded:
        rel = jnp.logical_and(rel, in_range)
    pl.when(rel)(_block)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[...][:, :1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[...] / denom).astype(o_ref.dtype)
        # Row logsumexp for the fused backward: L = m + log(l), -inf on
        # fully-masked rows (the bwd kernels turn those into p = 0).
        m = m_ref[...][:, :1]
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(denom))
        lse_ref[0, 0, :, :] = jnp.broadcast_to(
            lse, (lse.shape[0], LSE_LANES))


def _flash_forward(q, k, v, *, causal, window, q_offset, k_offset,
                   block_q, block_k, interpret):
    """[B, S, H, D] flash attention forward via pallas_call.

    Returns `(out [B, Sq, H, D], lse [B, H, nq*bq, LSE_LANES] f32)` —
    the row logsumexp rides along for the fused Pallas backward
    (head-major, lane-replicated, padded to the block grid; -inf on
    fully-masked rows)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    group = _gqa_group(q, k, v)
    # Snapped tiles: multi-block tiles must be 8-aligned for real
    # Mosaic (v5e/v5-lite divisibility; BENCH_builder_r04) — see
    # `_snap_tile` / `flash_tile_check`.
    bq = _snap_tile(block_q, Sq)
    bk = _snap_tile(block_k, Sk)
    nq = -(-Sq // bq)
    nk = -(-Sk // bk)

    # Head-major layout for the kernel; XLA fuses the transposes.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if nq * bq != Sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, nq * bq - Sq), (0, 0)))
    if nk * bk != Sk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, nk * bk - Sk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, nk * bk - Sk), (0, 0)))

    # Sliding window: shrink the innermost grid to the k-blocks that
    # can intersect each q-block's band — out-of-band K/V blocks are
    # never DMA'd at all, so a long-context SWA step moves
    # O(S·(window+block)) bytes instead of O(S²).
    banded = causal and window is not None
    if banded:
        span = bq + window - 1                 # key span of one q-block
        nkb = min(nk, -(-span // bk) + 1)

        def k_map(b, h, i, j):
            j0 = _band_j0(i, window=window, q_offset=q_offset,
                          k_offset=k_offset, block_q=bq, block_k=bk)
            return (b, h // group, jnp.minimum(j0 + j, nk - 1), 0)
    else:
        nkb = nk

        def k_map(b, h, i, j):
            return (b, h // group, j, 0)

    kernel = functools.partial(
        _flash_kernel, scale=D ** -0.5, causal=causal, window=window,
        banded=banded, nk_total=nk,
        q_offset=q_offset, k_offset=k_offset, kv_len=Sk,
        block_q=bq, block_k=bk)

    grid = (B, H, nq, nkb)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), k_map),
            pl.BlockSpec((1, 1, bk, D), k_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            _sds((B, H, nq * bq, D), q.dtype, qt, kt, vt),
            _sds((B, H, nq * bq, LSE_LANES), jnp.float32, qt, kt, vt),
        ],
        scratch_shapes=[
            _scratch((bq, D), jnp.float32),
            _scratch((bq, 128), jnp.float32),
            _scratch((bq, 128), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(),
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :, :Sq, :]
    # lse stays rank-4 (lane-replicated) so a fused backward can DMA it
    # straight back in without a 128x re-broadcast; public surfaces
    # slice `[..., 0]`.
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _scratch(shape, dtype):
    if _VMEM is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable")
    return _VMEM(shape, dtype)


def _gqa_group(q, k, v):
    """q heads per kv head (GQA, Ainslie et al. 2023) — the kernels
    index-map K/V head `h // group`, so grouped K/V is consumed
    NATIVELY, never materialized at full head count in HBM."""
    H, Hkv = q.shape[2], k.shape[2]
    if v.shape[2] != Hkv:
        raise ValueError(
            f"k and v head counts differ: {Hkv} vs {v.shape[2]}")
    if H % Hkv:
        raise ValueError(
            f"query heads ({H}) must be a multiple of kv heads "
            f"({Hkv}) for grouped-query attention")
    return H // Hkv


def _sds(shape, dtype, *like):
    """ShapeDtypeStruct whose varying-manual-axes are the union of the
    `like` operands' — lets the pallas_calls sit inside `shard_map`
    with its default `check_vma=True` (ring/Ulysses SP pass this
    kernel as `attn_impl`)."""
    vma = frozenset()
    for x in like:
        try:
            vma |= jax.typeof(x).vma
        except (AttributeError, TypeError):
            pass   # older jax (no typeof/vma) / non-shard_map tracer
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _recompute_p(q_ref, k_ref, lse_ref, *, scale, causal, window,
                 kv_len, q_start, k_start, k_local0, block_q, block_k):
    """Shared bwd-kernel tile: rebuild the probability block
    `p = exp(scale·q·kᵀ − lse)` exactly as the forward computed it
    (same f32 dot, same mask, -inf lse rows → 0)."""
    qs = q_ref[0, 0].astype(jnp.float32) * scale           # [bq, D]
    kb = k_ref[0, 0].astype(jnp.float32)                   # [bk, D]
    s = jax.lax.dot_general(qs, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    mask = _mask_block(q_start, k_start, causal=causal, window=window,
                       kv_len=kv_len, k_local0=k_local0,
                       block_q=block_q, block_k=block_k)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    lse = lse_ref[0, 0, :, :1]                             # [bq, 1]
    p = jnp.where(jnp.isfinite(lse),
                  jnp.exp(s - lse), 0.0)                   # [bq, bk]
    return qs, kb, p


def _relevant_block(q_start, k_start, *, causal, window, block_q,
                    block_k):
    """Causal/window block-skip predicate shared by the forward and
    both backward kernels (~2x for long causal sequences); None when
    nothing can be skipped."""
    if not causal:
        return None
    rel = k_start <= q_start + block_q - 1
    if window is not None:
        rel = jnp.logical_and(
            rel, k_start + block_k - 1 >= q_start - window + 1)
    return rel


def _flash_bwd_dkv_kernel(q_ref, do_ref, lse_ref, dvec_ref, k_ref,
                          v_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                          scale, causal, window, banded, nq_total,
                          nq_band, q_offset, k_offset,
                          kv_len, block_q, block_k):
    """dK/dV: grid (B, Hkv, k-block, group·q-block) — the innermost
    sequential sweep runs every (gqa-group, q-block) pair, so the
    accumulators fold the whole query-head group in VMEM scratch and
    each dK/dV block is written to HBM exactly once AT KV WIDTH (with
    GQA there is no full-H gradient materialization + reduce pass).

    ``banded``: the q sweep covers only the blocks whose rows can see
    this k-block under the sliding-window band (index_map adds
    `_band_i0`; clamped duplicates skipped by the validity guard)."""
    j = pl.program_id(2)
    inner = pl.program_id(3)
    nin = pl.num_programs(3)
    qi = inner % nq_band       # q-block within this query head
    # (inner // nq_band = the group member; only index maps need it)

    @pl.when(inner == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if banded:
        il = _band_i0(j, q_offset=q_offset, k_offset=k_offset,
                      block_q=block_q, block_k=block_k) + qi
        ic = jnp.minimum(il, nq_total - 1)   # what the index_map DMA'd
        in_range = il <= nq_total - 1
    else:
        ic = qi
        in_range = True
    q_start = q_offset + ic * block_q
    k_start = k_offset + j * block_k

    def _block():
        qs, kb, p = _recompute_p(
            q_ref, k_ref, lse_ref, scale=scale, causal=causal,
            window=window, kv_len=kv_len, q_start=q_start,
            k_start=k_start, k_local0=j * block_k,
            block_q=block_q, block_k=block_k)
        dob = do_ref[0, 0].astype(jnp.float32)             # [bq, D]
        dv_acc[...] += jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, D]
        vb = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - dvec_ref[0, 0, :, :1])
        # s = (scale·q)·kᵀ, so dk = dsᵀ·(scale·q) — qs carries scale.
        dk_acc[...] += jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, D]

    rel = _relevant_block(q_start, k_start, causal=causal, window=window,
                        block_q=block_q, block_k=block_k)
    if rel is None:  # traced guard; see _flash_kernel
        rel = jnp.asarray(j) * block_k < kv_len
    if banded:
        rel = jnp.logical_and(rel, in_range)
    pl.when(rel)(_block)

    @pl.when(inner == nin - 1)
    def _fin():
        dk_ref[0, 0, :, :] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, dvec_ref, k_ref,
                         v_ref, dq_ref, dq_acc, *,
                         scale, causal, window, banded, nk_total,
                         q_offset, k_offset,
                         kv_len, block_q, block_k):
    """dQ: grid (B, H, q-block, k-block) with the k sweep innermost.

    ``banded``: same banded k sweep as the forward (`_band_j0`)."""
    qi = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if banded:
        jl = _band_j0(qi, window=window, q_offset=q_offset,
                      k_offset=k_offset, block_q=block_q,
                      block_k=block_k) + j
        jc = jnp.minimum(jl, nk_total - 1)
        in_range = jl <= nk_total - 1
    else:
        jc = j
        in_range = True
    q_start = q_offset + qi * block_q
    k_start = k_offset + jc * block_k

    def _block():
        qs, kb, p = _recompute_p(
            q_ref, k_ref, lse_ref, scale=scale, causal=causal,
            window=window, kv_len=kv_len, q_start=q_start,
            k_start=k_start, k_local0=jc * block_k,
            block_q=block_q, block_k=block_k)
        dob = do_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - dvec_ref[0, 0, :, :1])
        dq_acc[...] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, D]

    rel = _relevant_block(q_start, k_start, causal=causal, window=window,
                        block_q=block_q, block_k=block_k)
    if rel is None:  # traced guard; see _flash_kernel
        rel = jnp.asarray(jc) * block_k < kv_len
    if banded:
        rel = jnp.logical_and(rel, in_range)
    pl.when(rel)(_block)

    @pl.when(j == nk - 1)
    def _fin():
        # dq = scale · Σ_j ds·k (ds was taken w.r.t. scale·q·kᵀ).
        dq_ref[0, 0, :, :] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, *, causal, window, q_offset,
                    k_offset, block_q, block_k, interpret, dlse=None):
    """Fused Pallas backward (FlashAttention-2 style): recompute each
    probability tile from Q/K and the saved row logsumexp, never
    materializing [Sq, Sk] — two kernels (dK/dV with q innermost, dQ
    with k innermost), each output written once.

    vs the XLA recompute VJP it replaces on this path: no per-block
    scan residuals in HBM and no [B,Sq,H,D]-carry rewrite per k-block
    — the HBM traffic drops to the tensors themselves, which is what
    makes the fwd+bwd step time land near the ~2.5x-of-forward ideal.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    # Same snapped tiles as the forward (v5-lite divisibility).
    bq = _snap_tile(block_q, Sq)
    bk = _snap_tile(block_k, Sk)
    nq = -(-Sq // bq)
    nk = -(-Sk // bk)

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    ot = jnp.transpose(o, (0, 2, 1, 3))
    gt = jnp.transpose(g, (0, 2, 1, 3))
    if nq * bq != Sq:
        pad = ((0, 0), (0, 0), (0, nq * bq - Sq), (0, 0))
        qt, ot, gt = jnp.pad(qt, pad), jnp.pad(ot, pad), jnp.pad(gt, pad)
    if nk * bk != Sk:
        pad = ((0, 0), (0, 0), (0, nk * bk - Sk), (0, 0))
        kt, vt = jnp.pad(kt, pad), jnp.pad(vt, pad)
    # D_i = Σ_d dO_id · O_id (rowwise) — the softmax-jacobian term;
    # cheap elementwise+reduce, XLA fuses it into the transposes.
    # When the row logsumexp is itself an output with a cotangent
    # (`flash_attention_lse`, e.g. under a ring merge):
    # ∂lse_i/∂s_ij = p_ij, so ds = p·(dp − (D − dlse)) — the same
    # kernels run with dvec = D − dlse.
    dvec = (gt.astype(jnp.float32) * ot.astype(jnp.float32)).sum(-1)
    if dlse is not None:
        dvec = dvec - dlse.astype(jnp.float32)
    # dvec is born rank-3 here; lane-replicate it for Mosaic (see
    # LSE_LANES). lse arrives already rank-4 from the forward.
    dvec = jnp.broadcast_to(dvec[..., None], (*dvec.shape, LSE_LANES))

    # Sliding window: both sweeps shrink to the band, mirroring the
    # forward grid — out-of-band blocks are never DMA'd.
    banded = causal and window is not None
    group = _gqa_group(q, k, v)
    if banded:
        nkb = min(nk, -(-(bq + window - 1) // bk) + 1)
        nqb = min(nq, -(-(bk + window - 1) // bq) + 1)

        def dq_k_map(b, h, i, j):
            j0 = _band_j0(i, window=window, q_offset=q_offset,
                          k_offset=k_offset, block_q=bq, block_k=bk)
            return (b, h // group, jnp.minimum(j0 + j, nk - 1), 0)

        def dkv_q_map(b, hkv, j, inner):
            i0 = _band_i0(j, q_offset=q_offset, k_offset=k_offset,
                          block_q=bq, block_k=bk)
            i = jnp.minimum(i0 + inner % nqb, nq - 1)
            return (b, hkv * group + inner // nqb, i, 0)
    else:
        nkb, nqb = nk, nq

        def dq_k_map(b, h, i, j):
            return (b, h // group, j, 0)

        def dkv_q_map(b, hkv, j, inner):
            return (b, hkv * group + inner // nqb, inner % nqb, 0)

    common = dict(scale=D ** -0.5, causal=causal, window=window,
                  banded=banded, q_offset=q_offset, k_offset=k_offset,
                  kv_len=Sk, block_q=bq, block_k=bk)
    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    r_spec = pl.BlockSpec((1, 1, bq, LSE_LANES),
                          lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, nk_total=nk, **common),
        grid=(B, H, nq, nkb),
        in_specs=[
            q_spec, q_spec, r_spec, r_spec,
            pl.BlockSpec((1, 1, bk, D), dq_k_map),
            pl.BlockSpec((1, 1, bk, D), dq_k_map),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=_sds((B, H, nq * bq, D), q.dtype, qt, gt, kt, vt),
        scratch_shapes=[_scratch((bq, D), jnp.float32)],
        compiler_params=None if interpret else _compiler_params(),
        interpret=interpret,
    )(qt, gt, lse, dvec, kt, vt)

    kq_spec = pl.BlockSpec((1, 1, bq, D), dkv_q_map)
    kr_spec = pl.BlockSpec((1, 1, bq, LSE_LANES), dkv_q_map)
    kk_spec = pl.BlockSpec((1, 1, bk, D),
                           lambda b, hkv, j, inner: (b, hkv, j, 0))
    Hkv = H // group
    # Grid over KV heads; the inner sequential sweep folds the whole
    # query-head group into the VMEM accumulators, so dK/dV are
    # written once, at kv width — no full-H gradient + reduce pass.
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, nq_total=nq,
                          nq_band=nqb, **common),
        grid=(B, Hkv, nk, group * nqb),
        in_specs=[kq_spec, kq_spec, kr_spec, kr_spec,
                  kk_spec, kk_spec],
        out_specs=[kk_spec, kk_spec],
        out_shape=[
            _sds((B, Hkv, nk * bk, D), k.dtype, qt, gt, kt, vt),
            _sds((B, Hkv, nk * bk, D), v.dtype, qt, gt, kt, vt),
        ],
        scratch_shapes=[_scratch((bk, D), jnp.float32),
                        _scratch((bk, D), jnp.float32)],
        compiler_params=None if interpret else _compiler_params(),
        interpret=interpret,
    )(qt, gt, lse, dvec, kt, vt)

    dq = jnp.transpose(dq[:, :, :Sq], (0, 2, 1, 3))
    dk = jnp.transpose(dk[:, :, :Sk], (0, 2, 1, 3))
    dv = jnp.transpose(dv[:, :, :Sk], (0, 2, 1, 3))
    return dq, dk, dv


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _make_flash(causal, window, q_offset, k_offset, block_q, block_k,
                interpret, bwd_impl="pallas"):
    """Config-specialized flash fn with a fused or recompute VJP.

    ``bwd_impl="pallas"`` (the default): the FlashAttention-2 style
    fused backward (`_flash_backward`) — probability tiles rebuilt
    from the saved row logsumexp in two Pallas kernels, O(S) residual
    memory (q, k, v, o, lse), no XLA scan-residual traffic; banded
    sweeps under a sliding window.

    ``bwd_impl="recompute"``: differentiate the blockwise
    online-softmax scan (`sequence.blockwise_attention`, the same
    math) — the conservative fallback (HOROVOD_FLASH_BWD=recompute).
    With a sliding window the recompute backward is BANDED like the
    forward (`_banded_bwd`): Q is scanned in `block_q` chunks and
    each chunk's VJP sees only the `block_q + window - 1` keys its
    band can touch, so SWA training moves O(S·(window+block))
    bytes/FLOPs end to end, not O(S²).
    """
    from horovod_tpu.parallel.sequence import blockwise_attention

    def ref(q, k, v):
        # GQA: repeat kv INSIDE the vjp'd fn — jnp.repeat's transpose
        # is the per-group sum, so dk/dv come back at kv-head width.
        g_ = q.shape[2] // k.shape[2]
        if g_ > 1:
            k = jnp.repeat(k, g_, axis=2)
            v = jnp.repeat(v, g_, axis=2)
        return blockwise_attention(
            q, k, v, block_size=block_k, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset)

    def _banded_bwd(q, k, v, g):
        B, Sq, H, D = q.shape
        Sk = k.shape[1]
        C = min(block_q, Sq)
        span = C + window - 1          # keys one q-chunk's band touches
        nc = -(-Sq // C)
        pad_q = nc * C - Sq
        if pad_q:
            # Padded q rows sit past the real sequence; their cotangent
            # rows are zero, so every gradient contribution they make
            # vanishes (dq row-local; dk/dv weighted by g rows).
            q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
            g = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0), (0, 0)))

        def body(carry, ci):
            dq_a, dk_a, dv_a = carry
            qc = jax.lax.dynamic_slice_in_dim(q, ci * C, C, axis=1)
            gc = jax.lax.dynamic_slice_in_dim(g, ci * C, C, axis=1)
            # First key the chunk's band can touch, clamped so the
            # static-size slice stays in range; the k_offset handed to
            # the ref keeps masking exact under the clamp (keys pulled
            # into the slice but outside the band are masked out).
            lo = q_offset + ci * C - (window - 1) - k_offset
            start = jnp.clip(lo, 0, Sk - span)
            kc = jax.lax.dynamic_slice_in_dim(k, start, span, axis=1)
            vc = jax.lax.dynamic_slice_in_dim(v, start, span, axis=1)
            g_ = qc.shape[2] // kc.shape[2]

            def fn(qc, kc, vc, _start=start, _g=g_):
                if _g > 1:  # GQA (see `ref`)
                    kc = jnp.repeat(kc, _g, axis=2)
                    vc = jnp.repeat(vc, _g, axis=2)
                return blockwise_attention(
                    qc, kc, vc, block_size=block_k, causal=True,
                    window=window, q_offset=q_offset + ci * C,
                    k_offset=k_offset + _start)
            _, vjp = jax.vjp(fn, qc, kc, vc)
            dqc, dkc, dvc = vjp(gc)
            dq_a = jax.lax.dynamic_update_slice_in_dim(
                dq_a, dqc.astype(jnp.float32), ci * C, axis=1)
            # Adjacent bands overlap by window-1 keys: read-add-write.
            dk_a = jax.lax.dynamic_update_slice_in_dim(
                dk_a, jax.lax.dynamic_slice_in_dim(dk_a, start, span, 1)
                + dkc.astype(jnp.float32), start, axis=1)
            dv_a = jax.lax.dynamic_update_slice_in_dim(
                dv_a, jax.lax.dynamic_slice_in_dim(dv_a, start, span, 1)
                + dvc.astype(jnp.float32), start, axis=1)
            return (dq_a, dk_a, dv_a), None

        z = (jnp.zeros(q.shape, jnp.float32),
             jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32))
        (dq, dk, dv), _ = jax.lax.scan(body, z, jnp.arange(nc))
        return (dq[:, :Sq].astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    def _fwd_full(q, k, v):
        return _flash_forward(
            q, k, v, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset,
            block_q=block_q, block_k=block_k, interpret=interpret)

    @jax.custom_vjp
    def flash(q, k, v):
        return _fwd_full(q, k, v)[0]

    if bwd_impl == "pallas":
        def fwd(q, k, v):
            out, lse = _fwd_full(q, k, v)
            return out, (q, k, v, out, lse)

        def bwd(res, g):
            q, k, v, o, lse = res
            return _flash_backward(
                q, k, v, o, lse, g, causal=causal, window=window,
                q_offset=q_offset, k_offset=k_offset,
                block_q=block_q, block_k=block_k, interpret=interpret)
    else:
        def fwd(q, k, v):
            return flash(q, k, v), (q, k, v)

        def bwd(res, g):
            q, k, v = res
            # Band the backward only when it shrinks the key span.
            if (causal and window is not None
                    and min(block_q, q.shape[1]) + window - 1
                    < k.shape[1]):
                return _banded_bwd(q, k, v, g)
            _, vjp = jax.vjp(ref, q, k, v)
            return vjp(g)

    flash.defvjp(fwd, bwd)
    return flash


@functools.lru_cache(maxsize=None)
def _make_flash_lse(causal, window, q_offset, k_offset, block_q,
                    block_k, interpret):
    """`(o, lse)`-returning flash with a fused VJP that honors a
    cotangent on lse (∂lse/∂s = p folds into the dvec term) — the
    primitive for cross-block softmax merging (ring attention)."""

    @jax.custom_vjp
    def flash_lse(q, k, v):
        o, lse = _flash_forward(
            q, k, v, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset,
            block_q=block_q, block_k=block_k, interpret=interpret)
        return o, lse[:, :, :q.shape[1], 0]

    def fwd(q, k, v):
        o, lse = _flash_forward(
            q, k, v, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset,
            block_q=block_q, block_k=block_k, interpret=interpret)
        return (o, lse[:, :, :q.shape[1], 0]), (q, k, v, o, lse)

    def bwd(res, cot):
        q, k, v, o, lse = res
        g, dlse = cot
        pad = lse.shape[2] - q.shape[1]
        if pad:
            dlse = jnp.pad(dlse, ((0, 0), (0, 0), (0, pad)))
        return _flash_backward(
            q, k, v, o, lse, g, causal=causal, window=window,
            q_offset=q_offset, k_offset=k_offset,
            block_q=block_q, block_k=block_k, interpret=interpret,
            dlse=dlse)

    flash_lse.defvjp(fwd, bwd)
    return flash_lse


def flash_attention_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                        *, causal: bool = False,
                        window: Optional[int] = None,
                        q_offset: int = 0, k_offset: int = 0,
                        block_q: int = 128, block_k: int = 128,
                        interpret: Optional[bool] = None):
    """Flash attention that ALSO returns the row logsumexp.

    Returns `(out [B, Sq, H, D], lse [B, H, Sq] float32)`; lse is -inf
    on fully-masked rows (their out rows are 0). Two partial
    attentions over disjoint key sets merge exactly via
    `m = max(lse1, lse2); w_i = exp(lse_i - m);
    out = Σ w_i·out_i / Σ w_i; lse = m + log Σ w_i` — how
    `parallel.sequence.ring_attention(block_impl="flash")` runs the
    Pallas kernel on every ring rotation. Differentiable in all of
    (out, lse); GQA-native like `flash_attention`.

    Fused-backward-only: the HOROVOD_FLASH_BWD=recompute escape hatch
    applies to `flash_attention`, not this entry point (the blockwise
    fallback has no lse output to differentiate through) — if the
    fused backward misbehaves, use `ring_attention(block_impl="xla")`
    instead."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    from horovod_tpu.parallel.sequence import check_window
    check_window(window)
    if interpret is None:
        interpret = _auto_interpret()
    fn = _make_flash_lse(bool(causal),
                         None if window is None else int(window),
                         int(q_offset), int(k_offset),
                         int(block_q), int(block_k), bool(interpret))
    return fn(q, k, v)


flash_attention_lse.native_gqa = True


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask=None, *, causal: bool = False,
                    window: Optional[int] = None,
                    q_offset: int = 0, k_offset: int = 0,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None,
                    bwd_impl: str = "auto") -> jax.Array:
    """Fused flash attention, [B, S, H, D] → [B, S, H, D].

    Args:
      q, k, v: [batch, seq, heads, head_dim] (any float dtype; compute is
        float32, output matches `q.dtype`). `head_dim` a multiple of 128
        keeps the MXU fully tiled; smaller values work but underfill lanes.
      mask: unsupported here (only `causal=`); pass explicit masks to
        `parallel.tensor.dot_product_attention`. Accepted positionally as
        None so the fn is drop-in for `ParallelSelfAttention.attn_fn`.
      causal: apply a causal mask using global positions
        `q_offset + i >= k_offset + j` (offsets support ring-attention
        style rotated blocks).
      window: sliding-window attention (last `window` positions only;
        requires causal; >= 1). Banded end to end: the FORWARD's
        innermost grid axis covers only the k-blocks intersecting each
        q-block's band (out-of-band K/V never read from HBM), and the
        recompute BACKWARD scans q in `block_q` chunks whose VJPs see
        only each band's `block_q + window - 1` keys — so an SWA
        training step moves O(S·(window+block)) bytes and FLOPs, not
        O(S²).
      block_q, block_k: VMEM tile sizes (128 matches the MXU; raise
        block_k to 256/512 when head_dim is small).
      interpret: run the kernel in interpreter mode (None = auto: True
        off-TPU, so the same tests run on the CPU mesh).
      bwd_impl: "auto" (default — the fused Pallas backward
        `_flash_backward`, banded under a sliding window), "pallas",
        or "recompute" (the blockwise-VJP fallback). The env var
        HOROVOD_FLASH_BWD overrides "auto" (escape hatch if the fused
        backward misbehaves on some toolchain).
    """
    if mask is not None:
        raise NotImplementedError(
            "flash_attention supports causal masking only; use "
            "dot_product_attention for arbitrary masks")
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    from horovod_tpu.parallel.sequence import check_window
    check_window(window)
    if interpret is None:
        interpret = _auto_interpret()
    if bwd_impl not in ("auto", "pallas", "recompute"):
        raise ValueError(
            f"bwd_impl must be auto|pallas|recompute, got {bwd_impl!r}")
    if bwd_impl == "auto":
        from horovod_tpu.runtime.config import env_raw
        env = env_raw("HOROVOD_FLASH_BWD")
        if env is not None and env not in ("pallas", "recompute"):
            # The escape hatch must never silently select the kernel
            # being escaped (e.g. a typo'd "recompue").
            raise ValueError(
                f"HOROVOD_FLASH_BWD must be pallas|recompute, "
                f"got {env!r}")
        # Default: fused Pallas backward everywhere — banded under a
        # sliding window, mirroring the forward grid.
        bwd_impl = env or "pallas"
    fn = _make_flash(bool(causal),
                     None if window is None else int(window),
                     int(q_offset), int(k_offset),
                     int(block_q), int(block_k), bool(interpret),
                     bwd_impl)
    return fn(q, k, v)


# K/V may carry fewer heads than Q (must divide): the kernels index-map
# kv head h//group instead of reading a materialized repeat
# (`parallel.tensor.ParallelSelfAttention` checks this marker).
flash_attention.native_gqa = True


# ---------------------------------------------------------------------------
# Flash-decode: single-tick attention against the KV cache.
# ---------------------------------------------------------------------------

def _decode_kernel(s_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *,
                   scale: float, block_k: int, hkv: int, grp: int):
    """One (batch, k-block) grid cell of the decode tick.

    The cache is consumed IN ITS STORED LAYOUT [B, W, Hkv, D] — a
    head-major transpose would itself read the whole cache, the exact
    traffic this kernel exists to avoid. Per kv-head 2D dots (grp q
    rows each) + one concatenated online-softmax update over the full
    [H, block] score matrix keep every op a plain Mosaic-lowerable
    2D primitive (the r4 lesson: interpret mode accepts shapes real
    Mosaic rejects — stick to [8k, 128m]-safe blocks).

    Scratch persists across the k-block sweep (innermost axis):
      acc_ref [H, D] f32, m_ref/l_ref [H, 128] f32 (lane-replicated).
    Scalar prefetch `s_ref`: [0] = number of VALID k-blocks for this
    tick, [1] = filled prefix length. Blocks past s_ref[0] are skipped
    (and the index_map clamps them onto the last valid block, whose
    re-fetch the pipeline elides) — per-tick HBM traffic follows the
    generated length, not the cache allocation.
    """
    j = pl.program_id(1)
    nblk = s_ref[0]
    length = s_ref[1]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _block():
        q = q_ref[0].astype(jnp.float32) * scale        # [H, D]
        kb = k_ref[0]                                   # [bk, Hkv, D]
        vb = v_ref[0]
        parts = []
        for h in range(hkv):
            qh = q[h * grp:(h + 1) * grp, :]
            kh = kb[:, h, :].astype(jnp.float32)        # [bk, D]
            parts.append(jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))    # [grp, bk]
        logits = parts[0] if hkv == 1 else jnp.concatenate(parts, 0)
        pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(pos < length, logits, NEG_INF)

        m_prev = m_ref[...]                             # [H, 128]
        l_prev = l_ref[...]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - shift[:, :1])              # [H, bk]
        corr = jnp.where(m_prev == NEG_INF, 0.0,
                         jnp.exp(m_prev - shift))
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv_parts = []
        for h in range(hkv):
            ph = p[h * grp:(h + 1) * grp, :]
            vh = vb[:, h, :].astype(jnp.float32)
            pv_parts.append(jax.lax.dot_general(
                ph, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))    # [grp, D]
        pv = pv_parts[0] if hkv == 1 else jnp.concatenate(pv_parts, 0)
        acc_ref[...] = acc_ref[...] * corr[:, :1] + pv
        m_ref[...] = m_new

    pl.when(j < nblk)(_block)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[...][:, :1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def flash_decode_attention(q: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, length: jax.Array, *,
                           block_k: int = 512,
                           interpret: Optional[bool] = None
                           ) -> jax.Array:
    """One decode tick of attention against the filled cache prefix.

    q [B, 1, H, D]; k_cache/v_cache [B, W, Hkv, D] (the linear decode
    cache, already containing the current token at position
    ``length - 1``); ``length`` traced int32 — the filled prefix
    length. Returns [B, 1, H, D] at q.dtype.

    One fused kernel per (batch, k-block): only the
    ceil(length/block_k) leading cache blocks are DMA'd (scalar-
    prefetched block count; clamped index_map + pipeline elision make
    the tail free), GQA consumed natively at Hkv width, online softmax
    in f32 VMEM scratch. The lax.fori_loop equivalent lives in
    `ParallelSelfAttention._prefix_attention` (`decode_prefix_impl=
    "lax"`, the default + oracle); this kernel removes that loop's
    per-iteration overhead. bf16/f32 caches only (int8 KV uses the lax
    path's per-block dequant).
    """
    if interpret is None:
        interpret = _auto_interpret()
    B, W, Hkv, D = k_cache.shape
    if q.ndim != 4 or q.shape[1] != 1:
        raise ValueError(f"flash_decode_attention wants q [B,1,H,D], "
                         f"got {q.shape}")
    H = q.shape[2]
    if H % Hkv:
        raise ValueError(f"H={H} not divisible by Hkv={Hkv}")
    grp = H // Hkv
    bk = min(block_k, W)
    if W % bk:
        raise ValueError(
            f"block_k={bk} must divide cache length {W}")
    nk = W // bk
    length = jnp.asarray(length, jnp.int32)
    scalars = jnp.stack([(length + bk - 1) // bk, length])

    q3 = q[:, 0]                                        # [B, H, D]
    kernel = functools.partial(_decode_kernel, scale=D ** -0.5,
                               block_k=bk, hkv=Hkv, grp=grp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, nk),
        in_specs=[
            # index_map args: (*grid_indices, *scalar_prefetch_refs) —
            # the scalar ref comes LAST (jax pallas TPU convention).
            pl.BlockSpec((1, H, D), lambda b, j, s: (b, 0, 0)),
            pl.BlockSpec((1, bk, Hkv, D),
                         lambda b, j, s: (b, jnp.minimum(j, s[0] - 1),
                                          0, 0)),
            pl.BlockSpec((1, bk, Hkv, D),
                         lambda b, j, s: (b, jnp.minimum(j, s[0] - 1),
                                          0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, s: (b, 0, 0)),
        scratch_shapes=[
            _scratch((H, D), jnp.float32),
            _scratch((H, 128), jnp.float32),
            _scratch((H, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(scalars, q3, k_cache, v_cache)
    return out[:, None]
