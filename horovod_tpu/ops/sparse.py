"""Sparse (embedding) gradient path.

Parity with the reference's `tf.IndexedSlices` dispatch
(`horovod/tensorflow/__init__.py:61-72`, exercised by
`examples/tensorflow_word2vec.py`): instead of densifying an embedding
gradient and allreducing it, allgather the (values, indices) pair — an
allreduce of the *represented* dense tensor at a fraction of the bytes.

On TPU the gathered slices ride a single `all_gather` over ICI; consumers
either keep the slices (optax-style sparse apply) or scatter-add them into
the dense table (`to_dense`), which XLA lowers to an efficient
one-hot-matmul/scatter on the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class IndexedSlices:
    """A sparse slice-set: `dense[indices[i]] += values[i]`.

    Mirror of `tf.IndexedSlices` for the JAX world.
    """
    values: jax.Array    # [nnz, ...]
    indices: jax.Array   # [nnz]
    dense_shape: Optional[Tuple[int, ...]] = None

    def to_dense(self) -> jax.Array:
        if self.dense_shape is None:
            raise ValueError("IndexedSlices.to_dense requires dense_shape")
        out = jnp.zeros(self.dense_shape,
                        dtype=jnp.asarray(self.values).dtype)
        return out.at[jnp.asarray(self.indices)].add(self.values)


def allreduce_indexed_slices(ts: IndexedSlices, average: bool = True,
                             name: Optional[str] = None) -> IndexedSlices:
    """Allreduce an IndexedSlices by allgathering values and indices.

    Parity: `horovod/tensorflow/__init__.py:61-72` — two allgathers, then
    divide gathered values by size when averaging.
    """
    from horovod_tpu.ops import eager
    from horovod_tpu.runtime import state as _state
    st = _state.check_initialized()
    values = eager.allgather(
        ts.values, name=None if name is None else name + "_values")
    indices = eager.allgather(
        ts.indices, name=None if name is None else name + "_indices")
    if average:
        values = values / jnp.asarray(st.size, dtype=values.dtype)
    return IndexedSlices(values, indices, ts.dense_shape)
