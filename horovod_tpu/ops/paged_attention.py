"""Paged-attention: decode attention that walks only FILLED KV blocks.

The paged serving pool (`serving.paging`, PR 7) made paged decode
bitwise-equal to the fixed slot pool by GATHERING each lane's whole
block table back into a linear [max_len] view every tick
(`models.transformer._paged_view`) — correct, but the gather touches
every allocated block whether or not the sequence ever filled it, so
at serving shapes the capacity winner was the latency loser
(BENCH_serving_pr7: paged TPOT p50 211 ms vs 76 ms fixed at equal KV
bytes). This module deletes that tax: attention reads the pool
THROUGH the block table, touching only the blocks the lane actually
filled, in two interchangeable forms:

* **`paged_prefix_attention`** (``impl="lax"``, the default and the
  oracle): a `lax.fori_loop` walk over ``walk_block``-token spans —
  each step takes exactly the table entries covering its span (a
  bounded gather of ``walk_block/block_size`` blocks, never the full
  table) and applies the SAME online-softmax update, in the same
  order, with the same masking constants, as
  `ParallelSelfAttention._prefix_attention` runs on the gathered
  view. Same values + same float-op order ⇒ the walk is BITWISE the
  legacy gather path (pinned by tests/test_paged_attention.py), so it
  can be the default without perturbing a single pinned token stream.
  Composes with GQA (``groups``), int8 KV (scale pools, per-block
  dequant via the one tested codec), S >= 1 (prefill chunks and the
  spec-decode verify block ride the same walk), and vmaps over the
  lane axis natively.
* **`paged_decode_attention`** (``impl="pallas"``): the fused Pallas
  kernel for the S=1 decode tick — one (lane, block) grid, the block
  table and per-lane filled-block counts scalar-prefetched so the
  index map DMAs pool blocks directly (skipped blocks clamp onto the
  last valid one, whose re-fetch the pipeline elides — the
  `flash_decode_attention` trick applied through a block table), the
  current token's K/V merged in-kernel at its block offset, online
  softmax in f32 VMEM scratch. Accumulation granularity is one pool
  block, so its bitwise oracle is the lax walk at
  ``walk_block == block_size`` (pinned in interpret mode on CPU CI —
  the same fallback that lets this file's kernels run under CPU
  tests). Batched over lanes via `jax.custom_batching.custom_vmap`
  (the pools must NOT carry the lane axis — one physical pool serves
  every lane), mirroring the r4 Mosaic lesson: every in-kernel op is
  a plain 2D primitive with [8k, 128m]-safe or array-equal blocks.

Dispatch policy lives with the caller
(`parallel.tensor.ParallelSelfAttention`): "pallas" engages only for
S=1, un-quantized caches, and a trivial mesh (a bare pallas_call is
opaque to GSPMD), falling back to the lax walk otherwise — the same
gating `decode_prefix_impl="pallas"` already uses for the linear
cache.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from horovod_tpu.annotations import hot_path
from horovod_tpu.ops.flash_attention import (
    _auto_interpret, _scratch, pltpu,
)

__all__ = ["paged_prefix_attention", "paged_decode_attention"]


def _read_span(pool, table, start, nblocks, block_size):
    """One ``nblocks * block_size``-row span of a lane's logical cache,
    read THROUGH the block table: take the covering table entries (a
    bounded gather — the operand is ``nblocks`` blocks, never the full
    table span) and lay the rows out exactly as `_paged_view` would
    ([1, span, ...] — same bytes, same order), so every downstream op
    sees values identical to the legacy gathered view's."""
    bids = lax.dynamic_slice_in_dim(table, start // block_size,
                                    nblocks)
    blk = jnp.take(pool, bids, axis=0)          # [r, 1, bs, ...]
    blk = jnp.moveaxis(blk, 1, 0)               # [1, r, bs, ...]
    return blk.reshape((1, nblocks * block_size) + blk.shape[3:])


@hot_path
def paged_prefix_attention(q, k_new, v_new, k_pool, v_pool, table,
                           fill, *, walk_block: int, groups: int = 1,
                           k_scale_pool=None, v_scale_pool=None,
                           compute_dtype=None):
    """Attention of ``q`` (positions ``fill .. fill+S-1``) against a
    paged cache, walking only the filled blocks of ``table``.

    q [1, S, H, D]; k_new/v_new [1, S, Hkv, D] — the CURRENT call's
    K/V rows (already rotated, already through the KV codec: exactly
    the bytes a gather-path view would hold at those positions),
    merged into their walked blocks so the accumulation order matches
    the gather path block for block. k_pool/v_pool
    [num_blocks, 1, block_size, Hkv, D] (the serving pool leaf
    layout); ``table`` [T] int32; ``fill`` traced int32. With int8 KV,
    the pools are int8 and ``k_scale_pool``/``v_scale_pool``
    [num_blocks, 1, block_size, Hkv] carry the per-(position, head)
    scales — dequantized per span via the one tested codec, exactly
    as `_cache_read_block` does on the view.

    ``walk_block`` is the accumulation granularity (must be a
    multiple of ``block_size``): at the model's ``decode_prefix_block``
    the walk is BITWISE `_prefix_attention` on the gathered view; at
    ``block_size`` it is the Pallas kernel's oracle. Returns
    [1, S, H, D] at q.dtype; per-call HBM traffic follows ``fill``,
    not the table span.
    """
    bs = int(k_pool.shape[2])
    if walk_block < bs or walk_block % bs:
        raise ValueError(
            f"walk_block ({walk_block}) must be a positive multiple "
            f"of the pool block size ({bs})")
    r = walk_block // bs
    S, H, D = q.shape[-3], q.shape[-2], q.shape[-1]
    dtype = q.dtype
    cdtype = compute_dtype or dtype
    q = q * jnp.asarray(D ** -0.5, dtype)
    fill = jnp.asarray(fill, jnp.int32)
    qpos = fill + jnp.arange(S, dtype=jnp.int32)           # [S]
    nblk = (fill + S + walk_block - 1) // walk_block       # traced
    neg = jnp.finfo(jnp.float32).min
    lead = q.shape[:-3]
    m0 = jnp.full((*lead, H, S), neg, jnp.float32)
    l0 = jnp.zeros((*lead, H, S), jnp.float32)
    a0 = jnp.zeros((*lead, H, S, D), jnp.float32)

    def read(pool, spool, new, start):
        blk = _read_span(pool, table, start, r, bs)
        if spool is not None:
            from horovod_tpu.ops.quantization import dequantize_int8
            sblk = _read_span(spool, table, start, r, bs)
            blk = dequantize_int8(blk, sblk, cdtype, axis=-1)
        # Merge the current call's rows at their positions — the
        # gather path's view holds them (the write lands before the
        # attention read), so the walked span must too, IN the same
        # accumulation step, for bitwise equality.
        rel = start + jnp.arange(walk_block, dtype=jnp.int32) - fill
        ins = (rel >= 0) & (rel < S)
        taken = jnp.take(new, jnp.clip(rel, 0, S - 1), axis=-3)
        blk = jnp.where(ins[:, None, None], taken, blk)
        if groups > 1:
            blk = jnp.repeat(blk, groups, axis=-2)
        return blk

    def body(j, carry):
        m, l, acc = carry
        start = j * walk_block
        kb = read(k_pool, k_scale_pool, k_new, start)
        vb = read(v_pool, v_scale_pool, v_new, start)
        logits = jnp.einsum("...qhd,...khd->...hqk", q, kb,
                            preferred_element_type=jnp.float32)
        kvpos = start + jnp.arange(walk_block, dtype=jnp.int32)
        keep = kvpos[None, :] <= qpos[:, None]             # [S, wb]
        logits = jnp.where(keep, logits, neg)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = (acc * alpha[..., None]
                   + jnp.einsum("...hqk,...khd->...hqd",
                                p.astype(vb.dtype), vb,
                                preferred_element_type=jnp.float32))
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nblk, body, (m0, l0, a0))
    out = acc / l[..., None]                        # [..., H, S, D]
    return jnp.swapaxes(out, -3, -2).astype(dtype)


# ---------------------------------------------------------------------------
# The fused Pallas decode kernel (S = 1).
# ---------------------------------------------------------------------------

def _paged_decode_kernel(s_ref, t_ref, q_ref, kn_ref, vn_ref, k_ref,
                         v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                         scale: float, block_size: int, hkv: int,
                         grp: int):
    """One (lane, block) grid cell: the lax walk's body at
    ``walk_block == block_size``, fused.

    Scalar prefetch: ``s_ref`` [L, 2] = (filled-block count, fill) per
    lane — the index map clamps skipped blocks onto the last valid one
    (re-fetch elided by the pipeline), so per-tick HBM traffic follows
    the lane's fill, not its table span; ``t_ref`` [L, T] is the block
    table the K/V index maps read. Per-kv-head 2D dots (the
    `_decode_kernel` shape discipline — Mosaic-lowerable primitives
    only); the current token's K/V rows are merged at their in-block
    offset with a broadcast select, so the accumulation matches the
    lax walk update for update."""
    lane = pl.program_id(0)
    j = pl.program_id(1)
    nblk = s_ref[lane, 0]
    fill = s_ref[lane, 1]
    neg = jnp.finfo(jnp.float32).min

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, neg)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _block():
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_size, 1), 0)                # [bs, 1]
        ins = pos == fill                                 # [bs, 1]
        q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)    # [H, D]
        parts = []
        for h in range(hkv):
            kh = k_ref[0, :, h, :]                        # [bs, D]
            kh = jnp.where(ins, kn_ref[0, h, :][None, :], kh)
            qh = q[h * grp:(h + 1) * grp, :]
            parts.append(jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))      # [grp, bs]
        logits = parts[0] if hkv == 1 else jnp.concatenate(parts, 0)
        keep = (j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)) <= fill
        logits = jnp.where(keep, logits, neg)

        m_prev = m_ref[...]                               # [H, 128]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new[:, :1])                # [H, bs]
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1,
                                              keepdims=True)
        pv_parts = []
        for h in range(hkv):
            vh = v_ref[0, :, h, :]                        # [bs, D]
            vh = jnp.where(ins, vn_ref[0, h, :][None, :], vh)
            ph = p[h * grp:(h + 1) * grp, :].astype(vh.dtype)
            pv_parts.append(jax.lax.dot_general(
                ph, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))      # [grp, D]
        pv = pv_parts[0] if hkv == 1 else jnp.concatenate(pv_parts, 0)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
        m_ref[...] = m_new

    pl.when(j < nblk)(_block)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...][:, :1]).astype(
            o_ref.dtype)


def _paged_decode_call(q, k_new, v_new, table, fill, k_pool, v_pool,
                       interpret):
    """The batched pallas_call: q [L, H, D], k_new/v_new [L, Hkv, D],
    table [L, T], fill [L], pools [nb, bs, Hkv, D]."""
    L, H, D = q.shape
    nb, bs, hkv, _ = k_pool.shape
    T = table.shape[1]
    grp = H // hkv
    fill = jnp.asarray(fill, jnp.int32)
    scalars = jnp.stack([(fill + 1 + bs - 1) // bs, fill], axis=1)
    kernel = functools.partial(
        _paged_decode_kernel, scale=D ** -0.5, block_size=bs,
        hkv=hkv, grp=grp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, T),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda l, j, s, t: (l, 0, 0)),
            pl.BlockSpec((1, hkv, D), lambda l, j, s, t: (l, 0, 0)),
            pl.BlockSpec((1, hkv, D), lambda l, j, s, t: (l, 0, 0)),
            pl.BlockSpec(
                (1, bs, hkv, D),
                lambda l, j, s, t: (t[l, jnp.minimum(j, s[l, 0] - 1)],
                                    0, 0, 0)),
            pl.BlockSpec(
                (1, bs, hkv, D),
                lambda l, j, s, t: (t[l, jnp.minimum(j, s[l, 0] - 1)],
                                    0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda l, j, s, t: (l, 0, 0)),
        scratch_shapes=[
            _scratch((H, D), jnp.float32),
            _scratch((H, 128), jnp.float32),
            _scratch((H, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, H, D), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(scalars, jnp.asarray(table, jnp.int32), q, k_new, v_new,
      k_pool, v_pool)


@functools.lru_cache(maxsize=None)
def _make_paged_decode(interpret: bool):
    """custom_vmap-wrapped single-lane kernel entry: under the serving
    tick's `jax.vmap` over lanes the batch rule fires, turning the
    lane axis into the kernel's leading grid dimension while the
    POOLS stay unbatched — one physical pool, L lanes walking it
    through their own tables (a naive vmap would have broadcast the
    pool per lane, materializing L copies of the very bytes the
    kernel exists not to touch)."""

    @jax.custom_batching.custom_vmap
    def paged_decode(q, k_new, v_new, table, fill, k_pool, v_pool):
        return _paged_decode_call(
            q[None], k_new[None], v_new[None], table[None],
            jnp.asarray(fill, jnp.int32)[None], k_pool, v_pool,
            interpret)[0]

    @paged_decode.def_vmap
    def _rule(axis_size, in_batched, q, k_new, v_new, table, fill,
              k_pool, v_pool):
        if in_batched[5] or in_batched[6]:
            raise NotImplementedError(
                "paged_decode_attention: the KV pools must not carry "
                "the vmapped lane axis (one shared pool serves every "
                "lane)")

        def bcast(x, batched):
            return x if batched else jnp.broadcast_to(
                x, (axis_size,) + jnp.shape(x))

        out = _paged_decode_call(
            bcast(q, in_batched[0]), bcast(k_new, in_batched[1]),
            bcast(v_new, in_batched[2]), bcast(table, in_batched[3]),
            bcast(jnp.asarray(fill, jnp.int32), in_batched[4]),
            k_pool, v_pool, interpret)
        return out, True

    return paged_decode


@hot_path
def paged_decode_attention(q, k_new, v_new, k_pool, v_pool, table,
                           fill, *, interpret: Optional[bool] = None):
    """One S=1 decode tick of paged attention, fused (Pallas).

    q [1, 1, H, D]; k_new/v_new [1, 1, Hkv, D] (the current token's
    rotated K/V); pools [num_blocks, 1, block_size, Hkv, D]; table
    [T]; fill traced int32. Returns [1, 1, H, D]. Accumulates at
    block_size granularity — bitwise the lax walk at
    ``walk_block == block_size`` (the interpret-mode oracle); only
    ceil((fill+1)/block_size) blocks are DMA'd. vmap over the lane
    axis dispatches ONE kernel with lanes as the leading grid dim
    (pools unbatched). Un-quantized caches only — int8 KV keeps the
    lax walk's per-block dequant.
    """
    if pltpu is None:
        raise RuntimeError("pallas TPU backend unavailable")
    if interpret is None:
        interpret = _auto_interpret()
    fn = _make_paged_decode(bool(interpret))
    out = fn(q[0, 0], k_new[0, 0], v_new[0, 0],
             jnp.asarray(table, jnp.int32), fill,
             k_pool[:, 0], v_pool[:, 0])
    return out[None, None]
