"""JAX adapter — the primary framework adapter.

The TPU-native analogue of the reference's TF adapter
(`horovod/tensorflow/__init__.py`): wrap an optimizer so gradients are
allreduce-averaged across the data-parallel mesh before being applied
(`DistributedOptimizer`, reference `:127-186`), and broadcast initial
parameters from a root rank so all workers start identically
(`broadcast_global_variables`, reference `:82-124`).

Where the reference intercepts `compute_gradients` on a
`tf.train.Optimizer`, here we wrap an `optax.GradientTransformation`:
its `update()` first performs a *fused* (bucketed) `psum` of the incoming
gradients over the mesh axis — tensor fusion riding ICI — then delegates
to the wrapped transformation. Sparse `IndexedSlices` leaves take the
allgather path (reference `:61-72`).
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import eager
from horovod_tpu.ops.fusion import (combiner_override_options,
                                    fused_allreduce_tree)
from horovod_tpu.ops.sparse import IndexedSlices
from horovod_tpu.runtime import state as _state
from horovod_tpu.runtime.config import config


def _axis_in_scope(axis_name: str) -> bool:
    """True when `axis_name` is bound by an enclosing shard_map/pmap trace."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def allreduce_gradients(grads: Any, *, axis_name: Optional[str] = None,
                        average: bool = True,
                        threshold: Optional[int] = None,
                        reduce_dtype: Optional[Any] = None) -> Any:
    """Fused allreduce of a gradient pytree.

    Inside shard_map (axis bound): bucketed `psum` per SURVEY §7 step 3,
    semantics of the reference's per-gradient `hvd.allreduce`
    (`horovod/tensorflow/__init__.py:164-186`) plus tensor fusion
    (`docs/tensor-fusion.md`). Outside any SPMD context it is the
    size()==1 no-op the reference also short-circuits (`:174`).
    Sparse `IndexedSlices` leaves dispatch to the allgather path.
    """
    axis = axis_name or config.mesh_axis_name
    if reduce_dtype is None and config.allreduce_dtype:
        reduce_dtype = jnp.dtype(config.allreduce_dtype)

    sparse_leaves = {}

    def _is_leaf(x):
        return isinstance(x, IndexedSlices)

    leaves, treedef = jax.tree.flatten(grads, is_leaf=_is_leaf)
    dense_idx = [i for i, l in enumerate(leaves)
                 if not isinstance(l, IndexedSlices)]

    if not _axis_in_scope(axis):
        return grads  # single-program / size-1 path

    dense = [leaves[i] for i in dense_idx]
    reduced_dense = fused_allreduce_tree(
        dense, axis_name=axis, average=average,
        threshold=threshold, reduce_dtype=reduce_dtype)
    out = list(leaves)
    for i, r in zip(dense_idx, reduced_dense):
        out[i] = r
    for i, l in enumerate(leaves):
        if isinstance(l, IndexedSlices):
            vals = lax.all_gather(l.values, axis, axis=0, tiled=True)
            idxs = lax.all_gather(l.indices, axis, axis=0, tiled=True)
            if average:
                vals = vals / lax.psum(jnp.ones((), vals.dtype), axis)
            out[i] = IndexedSlices(vals, idxs, l.dense_shape)
    return jax.tree.unflatten(treedef, out)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *, average: bool = True,
                         axis_name: Optional[str] = None,
                         fusion_threshold: Optional[int] = None,
                         reduce_dtype: Optional[Any] = None,
                         backward_passes_per_step: int = 1,
                         compression: Optional[str] = None,
                         compression_rank: int = 4
                         ) -> optax.GradientTransformation:
    """Wrap an optax transformation with gradient allreduce.

    Parity: `hvd.DistributedOptimizer` (`horovod/tensorflow/__init__.py:
    127-186`) — same contract (allreduce-average gradients, then delegate
    every other behavior to the wrapped optimizer), SPMD mechanics.

    ``backward_passes_per_step=k`` (later Horovod's gradient
    accumulation): local gradients accumulate for k microbatch steps
    (`optax.MultiSteps`) and the allreduce runs ONCE per k, on the
    accumulated mean — the bandwidth contract the name promises. The
    returned transformation is marked distributed either way, so
    `make_train_step` never adds a second allreduce on top.

    ``compression``: "fp16" = the reference's wire-dtype compression
    (`horovod/tensorflow/__init__.py:119-124` Compression.fp16 —
    sugar for ``reduce_dtype="float16"``); "powersgd" = rank-r
    factorized allreduce with error feedback
    (`ops.compression.powersgd_allreduce`, ``compression_rank``) —
    matrix gradients ship r·(n+m) floats instead of n·m.
    """
    if compression not in (None, "fp16", "powersgd"):
        raise ValueError(
            f"compression must be None|'fp16'|'powersgd', "
            f"got {compression!r}")
    if compression == "fp16" and reduce_dtype is None:
        reduce_dtype = jnp.float16

    if compression == "powersgd":
        if not average:
            raise ValueError(
                "compression='powersgd' averages by construction "
                "(the factor allreduces are means); average=False is "
                "not supported")
        from horovod_tpu.ops.compression import powersgd_allreduce
        compressor = powersgd_allreduce(
            rank=compression_rank, axis_name=axis_name,
            threshold=fusion_threshold, reduce_dtype=reduce_dtype)

        def init_fn(params):
            return (compressor.init(params), optimizer.init(params))

        def update_fn(updates, opt_state, params=None, **extra):
            c_state, in_state = opt_state
            updates, c_state = compressor.update(updates, c_state,
                                                 params)
            updates, in_state = optimizer.update(updates, in_state,
                                                 params, **extra)
            return updates, (c_state, in_state)
    else:
        def init_fn(params):
            return optimizer.init(params)

        def update_fn(updates, opt_state, params=None, **extra):
            updates = allreduce_gradients(
                updates, axis_name=axis_name, average=average,
                threshold=fusion_threshold, reduce_dtype=reduce_dtype)
            return optimizer.update(updates, opt_state, params, **extra)

    inner = _DistributedTransformation(init_fn, update_fn)
    if backward_passes_per_step > 1:
        ms = optax.MultiSteps(
            inner, every_k_schedule=backward_passes_per_step)

        def ms_update(updates, opt_state, params=None, **extra):
            # MultiSteps accumulates into dense zeros_like buffers;
            # an IndexedSlices leaf would hit an opaque tree-arith
            # error deep inside optax — refuse clearly instead.
            from horovod_tpu.ops.sparse import IndexedSlices
            leaves = jax.tree.leaves(
                updates,
                is_leaf=lambda x: isinstance(x, IndexedSlices))
            if any(isinstance(l, IndexedSlices) for l in leaves):
                raise NotImplementedError(
                    "backward_passes_per_step > 1 does not support "
                    "sparse IndexedSlices gradients (densify them or "
                    "accumulate at k=1)")
            return ms.update(updates, opt_state, params, **extra)

        return _DistributedTransformation(ms.init, ms_update)
    return inner


class _DistributedTransformation(optax.GradientTransformation):
    """Typed marker so make_train_step can tell an already-distributed
    transformation apart and not allreduce twice."""


class DistributedGradientTape:
    """Convenience value-and-grad wrapper (API familiarity with later
    Horovod's `hvd.DistributedGradientTape`): computes grads and
    allreduces them in one call."""

    def __init__(self, loss_fn: Callable, *, axis_name: Optional[str] = None,
                 average: bool = True):
        self._vg = jax.value_and_grad(loss_fn)
        self._axis = axis_name
        self._avg = average

    def __call__(self, params, *args, **kwargs):
        loss, grads = self._vg(params, *args, **kwargs)
        grads = allreduce_gradients(
            grads, axis_name=self._axis, average=self._avg)
        return loss, grads


def broadcast_global_variables(params: Any, root_rank: int = 0) -> Any:
    """Broadcast a parameter pytree from `root_rank` to all ranks.

    Parity: `broadcast_global_variables` (`horovod/tensorflow/__init__.py:
    82-90`). Single-controller: parameters are already globally consistent
    (one copy), so this replicates them over the mesh; multi-controller:
    a true cross-process broadcast so restored/initialized rank-0 weights
    win (the checkpoint/restore contract, SURVEY §5.4).
    """
    return jax.tree.map(
        lambda x: eager.broadcast(x, root_rank), params)


# Aliases matching later-Horovod naming (broadcast_parameters /
# broadcast_optimizer_state are the torch-API names for the same contract).
def broadcast_parameters(params: Any, root_rank: int = 0) -> Any:
    return broadcast_global_variables(params, root_rank)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    return broadcast_global_variables(opt_state, root_rank)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """Broadcast an arbitrary picklable object from root_rank (parity with
    later Horovod's `hvd.broadcast_object`; used for epoch counters etc.).
    """
    st = _state.check_initialized()
    if st.num_processes <= 1:
        return obj
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # Length exchange first (scalars agree in shape on every rank), then
    # the padded payload — broadcast requires identical shapes across
    # ranks, like the reference (`mpi_ops.cc:409-430`).
    n = int(np.asarray(eager.broadcast(
        np.int64(payload.size), root_rank, name="bcast_object_len")))
    buf = np.zeros(n, np.uint8)
    if st.process_rank == root_rank:
        buf[:] = payload[:n]
    out = np.asarray(eager.broadcast(buf, root_rank,
                                     name="bcast_object_payload"))
    return pickle.loads(out.tobytes())


def allgather_object(obj: Any) -> list:
    """Gather one picklable object per rank into a list ordered by rank
    (parity with later Horovod's `hvd.allgather_object`; pairs with
    `broadcast_object` for metric/metadata collection).

    Rides the variable-dim-0 allgather (`MPI_Allgatherv` semantics,
    reference `mpi_ops.cc:732-809`): each process contributes its
    pickled payload as a [len, 1] uint8 block plus a length row, so
    payloads of different sizes need no padding negotiation beyond the
    size exchange the allgather already does.
    """
    st = _state.check_initialized()
    world = st.num_processes if st.num_processes > 1 else st.size
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    if world <= 1:
        return [obj]
    if st.num_processes <= 1:
        # Single-controller SPMD: every rank holds the same object;
        # fresh copies, no gathered blob.
        data = payload.tobytes()
        return [pickle.loads(data) for _ in range(world)]
    sizes = np.asarray(eager.allgather(
        np.asarray([payload.size], np.int64),
        name="agather_object_len"))
    blob = np.asarray(eager.allgather(payload,
                                      name="agather_object_payload"))
    out, off = [], 0
    for n in sizes:
        out.append(pickle.loads(blob[off:off + int(n)].tobytes()))
        off += int(n)
    return out


def grouped_allreduce(tensors: Sequence[Any], average: bool = True,
                      name: Optional[str] = None) -> list:
    """Allreduce a list of tensors as one fused operation (later
    Horovod's `hvd.grouped_allreduce`): same-dtype tensors are packed
    into a single flat collective — explicit access to the fusion the
    `DistributedOptimizer` path applies automatically
    (`ops/fusion.py`, docs/tensor-fusion.md).
    """
    if any(isinstance(t, eager.PerRank) for t in tensors):
        raise TypeError(
            "grouped_allreduce takes plain arrays (one per call site), "
            "not per_rank inputs; allreduce each per_rank individually")
    arrs = [np.asarray(t) for t in tensors]
    out: list = [None] * len(arrs)
    # One collective per dtype, order-independent: the caller asked for
    # a grouped op, so all same-dtype tensors pack together even when
    # interleaved with other dtypes.
    by_dtype: dict = {}
    for i, a in enumerate(arrs):
        by_dtype.setdefault(a.dtype, []).append(i)
    # Packing erases per-tensor boundaries from the flat payload's
    # metadata ((2,)+(4,) vs (4,)+(2,): same flat shape!), so the FULL
    # group composition rides the control-plane negotiation of every
    # bucket as an opaque descriptor validated for cross-rank equality
    # — no extra data-plane collectives, and any disagreement (tensor
    # boundaries, dtype composition, ordering) raises crisply on the
    # first bucket. Buckets are named by ordinal, never by dtype, so
    # disagreeing ranks still negotiate under matching keys instead of
    # timing out on keys the peer never posts.
    desc = repr([(tuple(a.shape), str(a.dtype)) for a in arrs])
    for j, bucket in enumerate(by_dtype.values()):
        flat = np.concatenate([arrs[i].ravel() for i in bucket])
        red = np.asarray(eager.allreduce(
            flat, average=average,
            name=name and f"{name}_g{j}",
            _meta_extra=desc))
        off = 0
        for i in bucket:
            n = arrs[i].size
            out[i] = red[off:off + n].reshape(arrs[i].shape)
            off += n
    return out


def make_global_batch(batch: Any, *, axis_name: Optional[str] = None) -> Any:
    """Assemble per-process local batches into global arrays sharded over
    the data axis — how a multi-controller training loop feeds
    `make_train_step` (each process loads its own shard, the reference's
    per-worker data sharding pattern, `examples/keras_mnist_advanced.py:
    113-119`). A no-op returning device arrays in single-controller mode.
    """
    from jax.sharding import NamedSharding
    st = _state.check_initialized()
    if st.num_processes <= 1:
        return jax.tree.map(jnp.asarray, batch)
    sharding = NamedSharding(st.mesh, P(axis_name or st.axis_name))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), batch)


def make_train_step(loss_fn: Callable, tx: optax.GradientTransformation,
                    *, mesh=None, axis_name: Optional[str] = None,
                    fusion_threshold: Optional[int] = None,
                    reduce_dtype: Optional[Any] = None,
                    donate: bool = True) -> Callable:
    """Build the jitted SPMD data-parallel train step — the hot path
    (reference SURVEY §3.2), compiled once.

    loss_fn(params, batch) -> scalar loss over the *per-device* microbatch.
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where `batch` is sharded over the data axis and params/opt_state are
    replicated. Backprop and the fused psum overlap under XLA's async
    collectives — the latency hiding the reference builds by hand with
    its background thread + fusion buffer.
    """
    st = _state.check_initialized()
    mesh = mesh or st.mesh
    axis = axis_name or st.axis_name
    already_distributed = isinstance(tx, _DistributedTransformation)
    if already_distributed and (fusion_threshold is not None
                                or reduce_dtype is not None):
        # Same contract as make_cnn_train_step: the DistributedOptimizer
        # owns the allreduce, so the factory's wire knobs would be
        # silently dead — refuse instead.
        raise ValueError(
            "tx is an hvd.DistributedOptimizer, which owns the "
            "gradient allreduce — pass fusion_threshold/reduce_dtype "
            "to DistributedOptimizer(...) instead of the step factory")

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if not already_distributed:
            grads = allreduce_gradients(
                grads, axis_name=axis, threshold=fusion_threshold,
                reduce_dtype=reduce_dtype)
        loss = lax.pmean(loss, axis)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, loss

    sharded = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    from horovod_tpu.utils.timeline import step_bracket
    return step_bracket(jax.jit(
        sharded, donate_argnums=donate_argnums,
        compiler_options=combiner_override_options() or None))
