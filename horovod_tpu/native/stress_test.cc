// ThreadSanitizer stress harness for the native control plane + data
// loader — SURVEY §5.2 notes the reference has NO race-detection
// tooling (thread safety is by hand); this goes beyond parity: the
// same translation units Python loads are compiled with
// -fsanitize=thread and hammered from many threads. Run by
// tests/test_native.py::test_tsan_stress (skipped when TSan is
// unavailable) and ci.sh.
//
// Build: g++ -std=c++17 -fsanitize=thread -g -O1 \
//     control_plane.cc data_loader.cc stress_test.cc -o stress_test \
//     -lpthread
// Exit code 0 + "STRESS_OK" on stdout; TSan reports go to stderr and
// force a nonzero exit (halt_on_error in TSAN_OPTIONS).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int hvd_native_init(int rank, int size, int local_rank, int local_size);
int hvd_native_shutdown();
int hvd_native_rendezvous_serve(int port, int world);
void hvd_native_rendezvous_stop();
int hvd_native_client_connect(const char* host, int port, double timeout_s);
void hvd_native_client_close();
int hvd_native_kv_set(const char* key, const char* val, int vlen);
int hvd_native_kv_get(const char* key, long timeout_ms, char* out, int cap);
int hvd_native_barrier(const char* id, long timeout_ms);
int hvd_native_ping();
int hvd_native_timeline_start(const char* path);
void hvd_native_timeline_record(const char* tensor, const char* phase,
                                const char* activity);
void hvd_native_timeline_mark(const char* tensor, const char* name);
void hvd_native_timeline_stop();
void hvd_native_stall_configure(double warning_s, double check_every_s);
void hvd_native_stall_start_thread();
void hvd_native_stall_stop_thread();
void hvd_native_stall_begin(const char* name);
void hvd_native_stall_end(const char* name);

void* hvd_dl_open(const char** paths, int64_t nfiles, int64_t record_bytes,
                  int64_t batch_records, int64_t capacity, int shuffle,
                  uint64_t seed, int64_t rank, int64_t world,
                  int drop_remainder);
int hvd_dl_start_epoch(void* handle, uint64_t epoch);
int64_t hvd_dl_next(void* handle, uint8_t* out);
int64_t hvd_dl_num_records(void* handle);
const char* hvd_dl_error(void* handle);
void hvd_dl_close(void* handle);
}

static std::atomic<int> failures{0};
static std::string g_dir = "/tmp";  // scratch dir (argv[1] overrides)

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,     \
                   __LINE__, #cond);                                    \
      failures.fetch_add(1);                                            \
    }                                                                   \
  } while (0)

// Control plane: N threads share the process-global KV client (the
// Python binding's threading model) while the server runs in-process.
static void stress_control_plane() {
  CHECK(hvd_native_init(0, 1, 0, 1) == 0);
  int port = hvd_native_rendezvous_serve(0, 1);
  CHECK(port > 0);
  CHECK(hvd_native_client_connect("127.0.0.1", port, 10.0) == 0);

  std::string tl = g_dir + "/hvd_stress_timeline.json";
  hvd_native_timeline_start(tl.c_str());
  hvd_native_stall_configure(0.001, 0.001);
  hvd_native_stall_start_thread();

  const int kThreads = 8, kOps = 200;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([t] {
      char buf[64];
      for (int i = 0; i < kOps; ++i) {
        std::string key = "k" + std::to_string(t) + "_" +
                          std::to_string(i % 16);
        std::string val = "v" + std::to_string(i);
        CHECK(hvd_native_kv_set(key.c_str(), val.data(),
                                static_cast<int>(val.size())) == 0);
        int n = hvd_native_kv_get(key.c_str(), 2000, buf, sizeof(buf));
        CHECK(n > 0);
        CHECK(hvd_native_ping() == 0);
        std::string tensor = "t" + std::to_string(i % 4);
        hvd_native_timeline_record(tensor.c_str(), "NEGOTIATING",
                                   nullptr);
        hvd_native_timeline_record(tensor.c_str(), "TOP_LEVEL",
                                   "ALLREDUCE");
        hvd_native_timeline_mark(tensor.c_str(), "QUEUE");
        hvd_native_timeline_record(tensor.c_str(), "DONE", nullptr);
        hvd_native_stall_begin(tensor.c_str());
        hvd_native_stall_end(tensor.c_str());
        if (i % 32 == 0) {
          std::string b = "bar" + std::to_string(t) + "_" +
                          std::to_string(i);
          CHECK(hvd_native_barrier(b.c_str(), 2000) == 0);
        }
      }
    });
  }
  for (auto& th : ts) th.join();

  hvd_native_stall_stop_thread();
  hvd_native_timeline_stop();
  hvd_native_client_close();
  hvd_native_rendezvous_stop();
  hvd_native_shutdown();
}

// Data loader: producer thread vs consumer, abandoned epochs with a
// full prefetch queue, close() racing production — the surface where
// the round-1 advisor found the non-atomic abort_epoch flag.
static void stress_data_loader() {
  const int64_t kRecBytes = 64, kRecs = 256;
  std::string shard = g_dir + "/hvd_stress_shard.bin";
  const char* path = shard.c_str();
  FILE* f = fopen(path, "wb");
  CHECK(f != nullptr);
  std::vector<char> rec(kRecBytes, 7);
  for (int64_t i = 0; i < kRecs; ++i)
    fwrite(rec.data(), 1, rec.size(), f);
  fclose(f);

  const char* paths[] = {path};
  for (int round = 0; round < 6; ++round) {
    void* L = hvd_dl_open(paths, 1, kRecBytes, 8, /*capacity=*/2,
                          /*shuffle=*/1, /*seed=*/round, 0, 1,
                          /*drop_remainder=*/round % 2);
    CHECK(L != nullptr);
    CHECK(hvd_dl_num_records(L) == kRecs);
    std::vector<uint8_t> out(8 * kRecBytes);
    for (uint64_t e = 0; e < 4; ++e) {
      CHECK(hvd_dl_start_epoch(L, e) == 0);
      // Abandon some epochs mid-drain with the producer parked on the
      // full queue; drain others fully.
      int take = (e % 2 == 0) ? 3 : 1 << 20;
      int64_t n;
      while (take-- > 0 && (n = hvd_dl_next(L, out.data())) > 0) {
      }
    }
    hvd_dl_close(L);  // close with producer possibly mid-epoch
  }
  std::remove(path);
}

int main(int argc, char** argv) {
  if (argc > 1) g_dir = argv[1];
  stress_control_plane();
  stress_data_loader();
  if (failures.load() != 0) {
    std::fprintf(stderr, "STRESS_FAILED: %d checks\n", failures.load());
    return 1;
  }
  std::printf("STRESS_OK\n");
  return 0;
}
