"""ctypes bindings for the native control plane (built in-tree).

Placeholder until the C++ library lands; `load()` raising keeps
`hvd.init()` on the pure-Python fallback path.
"""

from __future__ import annotations


class NativeControlPlane:
    @classmethod
    def load(cls):
        raise ImportError("native control plane not built yet")
