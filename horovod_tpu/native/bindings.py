"""ctypes bindings for the native control plane.

Mirrors the reference's ctypes load of its compiled extension
(`horovod/tensorflow/mpi_ops.py:68-77`): one shared library, C ABI,
loaded RTLD_GLOBAL. Each wrapper converts to/from Python types; error
strings come back through caller-provided buffers.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

_ERR_CAP = 4096


class NativeControlPlane:
    def __init__(self, lib: ctypes.CDLL):
        self.lib = lib
        lib.hvd_native_rank.restype = ctypes.c_int
        lib.hvd_native_size.restype = ctypes.c_int
        lib.hvd_native_local_rank.restype = ctypes.c_int
        lib.hvd_native_local_size.restype = ctypes.c_int
        lib.hvd_native_validate.restype = ctypes.c_int
        lib.hvd_native_kv_get.restype = ctypes.c_int
        lib.hvd_native_rendezvous_serve.restype = ctypes.c_int
        lib.hvd_native_client_connect.restype = ctypes.c_int
        lib.hvd_native_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_double]
        lib.hvd_native_stall_configure.argtypes = [
            ctypes.c_double, ctypes.c_double]
        lib.hvd_native_kv_get.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_int]
        lib.hvd_native_barrier.argtypes = [ctypes.c_char_p, ctypes.c_long]

    @classmethod
    def load(cls) -> "NativeControlPlane":
        from horovod_tpu.native.build import build_if_needed
        path = build_if_needed()
        return cls(ctypes.CDLL(path, mode=ctypes.RTLD_GLOBAL))

    # --- membership ---

    def init(self, rank: int, size: int, local_rank: int,
             local_size: int) -> int:
        return self.lib.hvd_native_init(rank, size, local_rank, local_size)

    def rank(self) -> int:
        return self.lib.hvd_native_rank()

    def size(self) -> int:
        return self.lib.hvd_native_size()

    def local_rank(self) -> int:
        return self.lib.hvd_native_local_rank()

    def shutdown(self) -> int:
        return self.lib.hvd_native_shutdown()

    # --- validation (ConstructMPIResponse parity) ---

    def validate(self, name: str, op: str, dtypes: Sequence[str],
                 shapes: Sequence[Tuple[int, ...]],
                 root_ranks: Optional[Sequence[int]],
                 allow_dim0_mismatch: bool) -> Optional[str]:
        n = len(dtypes)
        c_dtypes = (ctypes.c_char_p * n)(
            *[d.encode() for d in dtypes])
        ndims = (ctypes.c_int * n)(*[len(s) for s in shapes])
        flat = [d for s in shapes for d in s]
        c_shapes = (ctypes.c_longlong * len(flat))(*flat)
        c_roots = ((ctypes.c_int * n)(*root_ranks)
                   if root_ranks is not None else None)
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.hvd_native_validate(
            name.encode(), op.encode(), n, c_dtypes, ndims, c_shapes,
            c_roots, int(allow_dim0_mismatch), err, _ERR_CAP)
        return err.value.decode() if rc else None

    # --- timeline ---

    def timeline_start(self, path: str) -> int:
        return self.lib.hvd_native_timeline_start(path.encode())

    def timeline_record(self, tensor: str, phase: str,
                        activity: Optional[str] = None) -> None:
        self.lib.hvd_native_timeline_record(
            tensor.encode(), phase.encode(),
            activity.encode() if activity else None)

    def timeline_mark(self, tensor: str, name: str) -> None:
        self.lib.hvd_native_timeline_mark(tensor.encode(), name.encode())

    def timeline_stop(self) -> None:
        self.lib.hvd_native_timeline_stop()

    # --- stall detector ---

    def stall_configure(self, warning_s: float,
                        check_every_s: float = 10.0) -> None:
        self.lib.hvd_native_stall_configure(warning_s, check_every_s)

    def stall_start_thread(self) -> None:
        self.lib.hvd_native_stall_start_thread()

    def stall_stop_thread(self) -> None:
        self.lib.hvd_native_stall_stop_thread()

    def stall_begin(self, name: str) -> None:
        self.lib.hvd_native_stall_begin(name.encode())

    def stall_end(self, name: str) -> None:
        self.lib.hvd_native_stall_end(name.encode())

    def stall_check(self) -> List[str]:
        out = ctypes.create_string_buffer(_ERR_CAP)
        n = self.lib.hvd_native_stall_check(out, _ERR_CAP)
        if n == 0:
            return []
        return out.value.decode().split(";")

    # --- rendezvous ---

    def serve(self, port: int, world: int) -> int:
        """Start the coordinator server; returns the bound port."""
        return self.lib.hvd_native_rendezvous_serve(port, world)

    def serve_stop(self) -> None:
        self.lib.hvd_native_rendezvous_stop()

    def connect(self, host: str, port: int, timeout_s: float = 60.0) -> bool:
        return self.lib.hvd_native_client_connect(
            host.encode(), port, timeout_s) == 0

    def close(self) -> None:
        self.lib.hvd_native_client_close()

    def kv_set(self, key: str, value: bytes) -> bool:
        return self.lib.hvd_native_kv_set(
            key.encode(), value, len(value)) == 0

    def kv_get(self, key: str, timeout_ms: int = 60000) -> Optional[bytes]:
        cap = 1 << 20
        while True:
            out = ctypes.create_string_buffer(cap)
            n = self.lib.hvd_native_kv_get(
                key.encode(), timeout_ms, out, cap)
            if n < 0:
                return None
            if n <= cap:
                return out.raw[:n]
            cap = n  # value larger than the buffer: retry at full size

    def barrier(self, barrier_id: str, timeout_ms: int = 60000) -> bool:
        return self.lib.hvd_native_barrier(
            barrier_id.encode(), timeout_ms) == 0

    def ping(self) -> bool:
        return self.lib.hvd_native_ping() == 0
