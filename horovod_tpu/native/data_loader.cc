// horovod_tpu native data loader.
//
// TPU-native input pipeline runtime. The reference keeps IO in Python
// (its examples feed numpy batches through session feeds); on TPU the
// host must hide IO latency behind device steps or the MXU starves, so
// this loader does the reference's per-worker dataset sharding
// (examples/keras_mnist_advanced.py:113-119 divides work by hvd.size())
// natively:
//
//   * fixed-size binary records in shard files,
//   * shards assigned round-robin by rank (file i -> rank i % world),
//   * reader threads fill a bounded prefetch queue of ready batches
//     (double buffering: the host reads batch k+1 while the device
//     runs step k),
//   * optional within-shard record shuffling, deterministic by
//     (seed, epoch) on every rank — a splitmix64-keyed stable sort,
//     chosen over std::shuffle because the SAME permutation is
//     reproducible from numpy in the pure-Python fallback
//     (horovod_tpu/data `_shuffle_perm`): native and fallback yield
//     bitwise-identical batch streams, the exact-resume contract,
//   * mid-epoch resume: hvd_dl_start_epoch_at skips the first
//     start_record entries of the (already shuffled) epoch order, so a
//     checkpointed data cursor restarts the stream at batch k without
//     re-reading batches 0..k-1 on the host.
//
// Plain C ABI consumed via ctypes (horovod_tpu/data), same pattern as
// control_plane.cc. Build: g++ -O2 -std=c++17 -shared -fPIC -pthread
// data_loader.cc -o libhorovod_tpu_data.so

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// splitmix64 finalizer (Steele et al.) — the shared shuffle key both
// this loader and the Python fallback compute. Permutation = stable
// sort of indices by Mix64(seed * GOLDEN + epoch + i); stable so ties
// (astronomically unlikely) break identically to numpy's stable
// argsort.
inline uint64_t Mix64(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

struct Batch {
  std::vector<uint8_t> data;
  int64_t records = 0;
};

struct Loader {
  std::vector<std::string> files;   // this rank's shards
  int64_t record_bytes = 0;
  int64_t batch_records = 0;
  int64_t capacity = 0;             // max prefetched batches
  uint64_t seed = 0;
  bool shuffle = false;
  bool drop_remainder = false;

  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<Batch> queue;
  bool epoch_done = false;          // producer finished current epoch
  // Atomic: written in hvd_dl_start_epoch under the mutex but read
  // lock-free from the producer's hot loop via Stopping().
  std::atomic<bool> abort_epoch{false};
  std::atomic<bool> closed{false};
  std::thread producer;
  std::string error;

  // Sets error under the lock and wakes the consumer — a consumer
  // already parked in hvd_dl_next must re-evaluate its predicate.
  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu);
    error = msg;
    not_empty.notify_all();
  }

  bool Stopping() const {
    return closed.load() || abort_epoch.load();
  }

  ~Loader() {
    {
      // Hold the mutex while flipping closed: a producer between
      // predicate check and park would otherwise miss the wakeup.
      std::lock_guard<std::mutex> lk(mu);
      closed.store(true);
      not_full.notify_all();
      not_empty.notify_all();
    }
    if (producer.joinable()) producer.join();
  }
};

// Reads one epoch: every record of every owned shard, in shuffled order
// when requested, packed into batches pushed to the bounded queue.
// `start_record` entries of the epoch order are skipped first (the
// exact-resume fast path: resume at batch k costs zero reads of
// batches 0..k-1).
void ProduceEpoch(Loader* L, uint64_t epoch, int64_t start_record) {
  std::vector<std::pair<int, int64_t>> order;  // (file idx, record idx)
  std::vector<int64_t> counts(L->files.size(), 0);
  for (size_t fi = 0; fi < L->files.size(); ++fi) {
    FILE* f = fopen(L->files[fi].c_str(), "rb");
    if (!f) {
      L->Fail("cannot open " + L->files[fi]);
      return;
    }
    fseek(f, 0, SEEK_END);
    int64_t bytes = ftell(f);
    fclose(f);
    counts[fi] = bytes / L->record_bytes;
    for (int64_t r = 0; r < counts[fi]; ++r) order.emplace_back(fi, r);
  }
  if (L->shuffle) {
    const uint64_t base = L->seed * 0x9E3779B97F4A7C15ULL + epoch;
    std::vector<uint64_t> keys(order.size());
    for (size_t i = 0; i < order.size(); ++i) keys[i] = Mix64(base + i);
    std::vector<size_t> perm(order.size());
    for (size_t i = 0; i < perm.size(); ++i) perm[i] = i;
    std::stable_sort(perm.begin(), perm.end(),
                     [&keys](size_t a, size_t b) {
                       return keys[a] < keys[b];
                     });
    std::vector<std::pair<int, int64_t>> shuffled(order.size());
    for (size_t i = 0; i < perm.size(); ++i) shuffled[i] = order[perm[i]];
    order.swap(shuffled);
  }
  if (start_record > 0) {
    order.erase(order.begin(),
                order.begin() + std::min<int64_t>(
                    start_record, static_cast<int64_t>(order.size())));
  }

  Batch cur;
  cur.data.reserve(L->batch_records * L->record_bytes);
  int open_idx = -1;
  FILE* f = nullptr;
  std::vector<uint8_t> rec(L->record_bytes);
  for (auto& [fi, ri] : order) {
    if (L->Stopping()) break;
    if (fi != open_idx) {
      if (f) fclose(f);
      f = fopen(L->files[fi].c_str(), "rb");
      open_idx = fi;
      if (!f) {
        L->Fail("cannot reopen " + L->files[fi]);
        return;
      }
    }
    // Sequential reads when unshuffled; seek per record otherwise.
    if (fseek(f, ri * L->record_bytes, SEEK_SET) != 0 ||
        fread(rec.data(), 1, L->record_bytes, f) !=
            static_cast<size_t>(L->record_bytes)) {
      if (f) fclose(f);
      L->Fail("short read in " + L->files[fi]);
      return;
    }
    cur.data.insert(cur.data.end(), rec.begin(), rec.end());
    if (++cur.records == L->batch_records) {
      std::unique_lock<std::mutex> lk(L->mu);
      L->not_full.wait(lk, [L] {
        return L->Stopping() ||
               static_cast<int64_t>(L->queue.size()) < L->capacity;
      });
      if (L->Stopping()) break;
      L->queue.push_back(std::move(cur));
      cur = Batch();
      cur.data.reserve(L->batch_records * L->record_bytes);
      L->not_empty.notify_one();
    }
  }
  if (f) fclose(f);
  if (!L->drop_remainder && cur.records > 0 && !L->Stopping()) {
    std::unique_lock<std::mutex> lk(L->mu);
    L->not_full.wait(lk, [L] {
      return L->Stopping() ||
             static_cast<int64_t>(L->queue.size()) < L->capacity;
    });
    if (!L->Stopping()) {
      L->queue.push_back(std::move(cur));
      L->not_empty.notify_one();
    }
  }
  std::lock_guard<std::mutex> lk(L->mu);
  L->epoch_done = true;
  L->not_empty.notify_all();
}

}  // namespace

extern "C" {

// Creates a loader over `nfiles` NUL-terminated shard paths. Shards are
// assigned to this rank round-robin (i % world == rank). Returns an
// opaque handle, or 0 on bad arguments.
void* hvd_dl_open(const char** paths, int64_t nfiles,
                  int64_t record_bytes, int64_t batch_records,
                  int64_t capacity, int shuffle, uint64_t seed,
                  int64_t rank, int64_t world, int drop_remainder) {
  if (nfiles <= 0 || record_bytes <= 0 || batch_records <= 0 ||
      world <= 0 || rank < 0 || rank >= world) {
    return nullptr;
  }
  auto* L = new Loader();
  for (int64_t i = 0; i < nfiles; ++i) {
    if (i % world == rank) L->files.emplace_back(paths[i]);
  }
  L->record_bytes = record_bytes;
  L->batch_records = batch_records;
  L->capacity = capacity > 0 ? capacity : 4;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->drop_remainder = drop_remainder != 0;
  return L;
}

// Starts producing epoch `epoch` in the background at record offset
// `start_record` of the (shuffled) epoch order — the data-cursor
// resume entry point. Call once per epoch, then drain with
// hvd_dl_next until it returns 0.
int hvd_dl_start_epoch_at(void* handle, uint64_t epoch,
                          int64_t start_record) {
  auto* L = static_cast<Loader*>(handle);
  if (!L || L->closed.load() || start_record < 0) return -1;
  // The previous epoch may have been abandoned mid-drain with its
  // producer parked on a full queue: abort it, join, and discard any
  // stale batches so epoch N+1 never serves epoch-N data.
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->abort_epoch = true;
    L->not_full.notify_all();
    L->not_empty.notify_all();
  }
  if (L->producer.joinable()) L->producer.join();
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->queue.clear();
    L->abort_epoch = false;
    L->epoch_done = false;
    L->error.clear();
  }
  L->producer = std::thread(ProduceEpoch, L, epoch, start_record);
  return 0;
}

// Back-compat entry: a full epoch from record 0.
int hvd_dl_start_epoch(void* handle, uint64_t epoch) {
  return hvd_dl_start_epoch_at(handle, epoch, 0);
}

// Copies the next prefetched batch into `out` (capacity
// batch_records*record_bytes). Returns the number of records copied,
// 0 at epoch end, -1 on error/closed (hvd_dl_error explains).
int64_t hvd_dl_next(void* handle, uint8_t* out) {
  auto* L = static_cast<Loader*>(handle);
  if (!L) return -1;
  std::unique_lock<std::mutex> lk(L->mu);
  L->not_empty.wait(lk, [L] {
    return L->closed.load() || !L->queue.empty() || L->epoch_done ||
           !L->error.empty();
  });
  if (L->closed.load() || !L->error.empty()) return -1;
  if (L->queue.empty()) return 0;  // epoch_done and drained
  Batch b = std::move(L->queue.front());
  L->queue.pop_front();
  L->not_full.notify_one();
  lk.unlock();
  std::memcpy(out, b.data.data(), b.data.size());
  return b.records;
}

// Number of records this rank owns across its shards (for
// steps-per-epoch math; reference keras_mnist_advanced.py:113-119).
int64_t hvd_dl_num_records(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  if (!L) return -1;
  int64_t total = 0;
  for (auto& path : L->files) {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    total += ftell(f) / L->record_bytes;
    fclose(f);
  }
  return total;
}

const char* hvd_dl_error(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  static thread_local std::string copy;
  if (!L) return "null handle";
  std::lock_guard<std::mutex> lk(L->mu);
  copy = L->error;
  return copy.c_str();
}

void hvd_dl_close(void* handle) {
  delete static_cast<Loader*>(handle);
}

}  // extern "C"
