// horovod_tpu native control plane.
//
// TPU-native equivalent of the reference's C++ core
// (horovod/tensorflow/mpi_ops.cc): on TPU the *data plane* is XLA
// collectives compiled by the SPMD partitioner, so what remains native is
// the host control plane the reference also hand-writes:
//
//   1. Membership C API with -1/uninitialized semantics
//      (mpi_ops.cc:1536-1563).
//   2. Cross-rank collective-request validation — the contract of the
//      coordinator's ConstructMPIResponse (mpi_ops.cc:266-474): dtype /
//      shape / root-rank agreement, allgather dim-0 exemption.
//   3. Chrome-trace timeline writer with the per-tensor
//      {UNKNOWN, NEGOTIATING, TOP_LEVEL, ACTIVITY} state machine
//      (timeline.h:37-42, timeline.cc:59-220), 1 s flush cadence.
//   4. Stall detector: pending-op table + background sweep thread with
//      the 60 s warning (mpi_ops.cc:228, 1150-1193).
//   5. TCP rendezvous: a tiny coordinator (key-value store + barrier)
//      replacing the reference's MPI_Send/Recv control messages on
//      TAG_NOTIFY (mpi_ops.cc:225, 1321-1371) for multi-process
//      bootstrap and eager-path metadata exchange.
//
// Exposed as a plain C ABI consumed via ctypes
// (horovod_tpu/native/bindings.py), mirroring the reference's
// ctypes.CDLL load (mpi_ops.py:68-77).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread control_plane.cc
//        -o libhorovod_tpu_core.so

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

double NowSeconds() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

// ---------------------------------------------------------------------------
// 1. Membership
// ---------------------------------------------------------------------------

struct Membership {
  std::atomic<bool> initialized{false};
  int rank = -1;
  int size = -1;
  int local_rank = -1;
  int local_size = -1;
};

Membership g_member;

// ---------------------------------------------------------------------------
// 3. Timeline
// ---------------------------------------------------------------------------

enum TensorState { UNKNOWN = 0, NEGOTIATING = 1, TOP_LEVEL = 2, ACTIVITY = 3 };

class Timeline {
 public:
  bool Start(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    file_ = std::fopen(path.c_str(), "w");
    if (!file_) return false;
    std::fputs("[\n", file_);
    start_ = NowSeconds();
    last_flush_ = start_;
    return true;
  }

  void Record(const std::string& tensor, const std::string& phase,
              const std::string& activity) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!file_) return;
    int pid = Pid(tensor);
    TensorState state = states_.count(tensor) ? states_[tensor] : UNKNOWN;
    if (phase == "NEGOTIATING") {
      Emit('B', "NEGOTIATE", pid, "");
      states_[tensor] = NEGOTIATING;
    } else if (phase == "TOP_LEVEL") {
      if (state == NEGOTIATING) Emit('E', "NEGOTIATE", pid, "");
      Emit('B', tensor, pid, "");
      states_[tensor] = TOP_LEVEL;
      if (!activity.empty()) {
        Emit('B', activity, pid, "");
        states_[tensor] = ACTIVITY;
      }
    } else if (phase == "DONE") {
      if (state == ACTIVITY) Emit('E', "", pid, "");
      if (state == TOP_LEVEL || state == ACTIVITY)
        Emit('E', tensor, pid, "");
      else if (state == NEGOTIATING)
        Emit('E', "NEGOTIATE", pid, "");
      states_[tensor] = UNKNOWN;
    }
    MaybeFlush();
  }

  void Mark(const std::string& tensor, const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!file_) return;
    int pid = Pid(tensor);
    std::fprintf(file_,
                 "{\"ph\": \"X\", \"name\": \"%s\", \"pid\": %d, "
                 "\"ts\": %lld, \"dur\": 0},\n",
                 Escape(name).c_str(), pid, TsUs());
    MaybeFlush();
  }

  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    if (!file_) return;
    std::fputs("{}]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
    pids_.clear();
    states_.clear();
  }

 private:
  int Pid(const std::string& tensor) {
    auto it = pids_.find(tensor);
    if (it != pids_.end()) return it->second;
    int pid = static_cast<int>(pids_.size());
    pids_[tensor] = pid;
    // Tensors are modeled as trace processes (timeline.cc:59-76).
    std::fprintf(file_,
                 "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
                 "\"args\": {\"name\": \"%s\"}},\n",
                 pid, Escape(tensor).c_str());
    return pid;
  }

  long long TsUs() {
    return static_cast<long long>((NowSeconds() - start_) * 1e6);
  }

  static std::string Escape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      if (c == '"' || c == '\\') out.push_back('\\');
      if (static_cast<unsigned char>(c) >= 0x20) out.push_back(c);
    }
    return out;
  }

  void Emit(char ph, const std::string& name, int pid,
            const std::string& args) {
    std::fprintf(file_,
                 "{\"ph\": \"%c\", \"name\": \"%s\", \"pid\": %d, "
                 "\"ts\": %lld%s},\n",
                 ph, Escape(name).c_str(), pid, TsUs(),
                 args.empty() ? "" : (", " + args).c_str());
  }

  void MaybeFlush() {
    double now = NowSeconds();
    if (now - last_flush_ >= 1.0) {  // timeline.h:35 flush cadence
      std::fflush(file_);
      last_flush_ = now;
    }
  }

  std::mutex mu_;
  std::FILE* file_ = nullptr;
  std::unordered_map<std::string, int> pids_;
  std::unordered_map<std::string, TensorState> states_;
  double start_ = 0, last_flush_ = 0;
};

// Globals are heap-allocated and intentionally leaked: running their
// destructors at static-teardown time while worker threads are parked on
// member mutexes/CVs is UB (and a joinable std::thread's destructor is
// std::terminate) — the classic cause of SIGABRT at exit in processes
// that never call hvd.shutdown().
Timeline& g_timeline = *new Timeline;

// ---------------------------------------------------------------------------
// 4. Stall detector
// ---------------------------------------------------------------------------

class StallMonitor {
 public:
  void Configure(double warning_s, double check_every_s) {
    std::lock_guard<std::mutex> lk(mu_);
    warning_s_ = warning_s;
    check_every_s_ = check_every_s;
  }

  void StartThread() {
    std::lock_guard<std::mutex> lk(mu_);
    if (running_) return;
    running_ = true;
    thread_ = std::thread([this] { Loop(); });
  }

  void StopThread() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!running_) return;
      running_ = false;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
  }

  void Begin(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    pending_[name] = NowSeconds();
  }

  void End(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    pending_.erase(name);
    warned_.erase(name);
  }

  // Writes ";"-joined stalled names into out; returns count.
  int Check(char* out, int cap) {
    std::vector<std::string> stalled;
    {
      std::lock_guard<std::mutex> lk(mu_);
      double now = NowSeconds();
      for (auto& kv : pending_) {
        if (now - kv.second > warning_s_ && !warned_.count(kv.first)) {
          stalled.push_back(kv.first);
          warned_.insert(kv.first);
        }
      }
    }
    if (!stalled.empty()) {
      // Message shape follows mpi_ops.cc:1166-1186.
      std::fprintf(stderr,
                   "WARNING: One or more tensors were submitted to be "
                   "reduced, gathered or broadcasted by subset of ranks and "
                   "are waiting for remainder of ranks for more than %d "
                   "seconds. This may indicate that different ranks are "
                   "trying to submit different tensors or that only subset "
                   "of ranks is submitting tensors, which will cause "
                   "deadlock.\nStalled ops:");
      for (auto& s : stalled) std::fprintf(stderr, " %s", s.c_str());
      std::fprintf(stderr, "\n");
    }
    std::string joined;
    for (size_t i = 0; i < stalled.size(); ++i) {
      if (i) joined += ";";
      joined += stalled[i];
    }
    if (out && cap > 0) {
      std::snprintf(out, cap, "%s", joined.c_str());
    }
    return static_cast<int>(stalled.size());
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (running_) {
      cv_.wait_for(lk, std::chrono::duration<double>(check_every_s_));
      if (!running_) break;
      lk.unlock();
      Check(nullptr, 0);
      lk.lock();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::thread thread_;
  bool running_ = false;
  double warning_s_ = 60.0;  // mpi_ops.cc:228
  double check_every_s_ = 10.0;
  std::map<std::string, double> pending_;
  std::set<std::string> warned_;
};

StallMonitor& g_stall = *new StallMonitor;

// ---------------------------------------------------------------------------
// 5. TCP rendezvous: key-value store + barrier
// ---------------------------------------------------------------------------

// Wire format: u32 length | u8 op | u32 klen | key | u32 vlen | val
// ops: 1=SET 2=GET(blocking, val=timeout_ms as decimal string)
//      3=BARRIER(key=barrier id) 4=PING
// Replies: u32 length | u8 status(0=ok,1=timeout/err) | u32 vlen | val

struct KvStore {
  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> data;
  std::unordered_map<std::string, int> read_count;
  std::unordered_map<std::string, int> barrier_count;
  std::unordered_map<std::string, int> barrier_generation;
  int world = 0;
};

class RendezvousServer {
 public:
  // Returns the bound port (0 on failure).
  int Serve(int port, int world) {
    kv_.world = world;
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return 0;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return 0;
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    int bound = ntohs(addr.sin_port);
    if (::listen(listen_fd_, 128) != 0) return 0;
    running_ = true;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return bound;
  }

  void Stop() {
    if (!running_) return;
    running_ = false;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    // Wake handlers parked in recv() (shutdown their sockets) or in a
    // kv condition wait (notify; predicates re-check running_), then
    // join — otherwise Stop() deadlocks on live connections.
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> lk(threads_mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
      to_join.swap(conn_threads_);
    }
    kv_.cv.notify_all();
    // Join without holding threads_mu_ — exiting handlers take it to
    // deregister their fd.
    for (auto& t : to_join)
      if (t.joinable()) t.join();
    std::lock_guard<std::mutex> lk(threads_mu_);
    conn_fds_.clear();
  }

  KvStore kv_;

 private:
  void AcceptLoop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> lk(threads_mu_);
      conn_fds_.insert(fd);
      conn_threads_.emplace_back([this, fd] { Handle(fd); });
    }
  }

  static bool ReadFull(int fd, void* buf, size_t n) {
    char* p = static_cast<char*>(buf);
    while (n) {
      ssize_t r = ::recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static bool WriteFull(int fd, const void* buf, size_t n) {
    const char* p = static_cast<const char*>(buf);
    while (n) {
      ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static void Reply(int fd, uint8_t status, const std::string& val) {
    uint32_t len = htonl(static_cast<uint32_t>(1 + 4 + val.size()));
    uint32_t vlen = htonl(static_cast<uint32_t>(val.size()));
    WriteFull(fd, &len, 4);
    WriteFull(fd, &status, 1);
    WriteFull(fd, &vlen, 4);
    if (!val.empty()) WriteFull(fd, val.data(), val.size());
  }

  void Handle(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (running_) {
      uint32_t len_n;
      if (!ReadFull(fd, &len_n, 4)) break;
      uint32_t len = ntohl(len_n);
      if (len < 9 || len > (64u << 20)) break;
      std::vector<char> buf(len);
      if (!ReadFull(fd, buf.data(), len)) break;
      uint8_t op = static_cast<uint8_t>(buf[0]);
      uint32_t klen = ntohl(*reinterpret_cast<uint32_t*>(&buf[1]));
      // 64-bit arithmetic: u32 sums wrap on corrupt frames and would
      // pass the bounds check into an out-of-bounds read.
      if (5ull + klen + 4ull > len) break;
      std::string key(&buf[5], klen);
      uint32_t vlen = ntohl(*reinterpret_cast<uint32_t*>(&buf[5 + klen]));
      if (9ull + klen + vlen > len) break;
      std::string val(&buf[9 + klen], vlen);

      if (op == 1) {  // SET
        {
          std::lock_guard<std::mutex> lk(kv_.mu);
          kv_.data[key] = val;
        }
        kv_.cv.notify_all();
        Reply(fd, 0, "");
      } else if (op == 2) {  // GET with timeout
        long timeout_ms = atol(val.c_str());
        std::unique_lock<std::mutex> lk(kv_.mu);
        bool ok = kv_.cv.wait_for(
            lk, std::chrono::milliseconds(timeout_ms),
            [&] { return !running_ || kv_.data.count(key) > 0; });
        ok = ok && kv_.data.count(key) > 0;
        std::string out = ok ? kv_.data[key] : "";
        // Negotiation entries ("req/...") are read exactly once per
        // process; reap after the world-th read so the store doesn't
        // grow per collective call (the reference coordinator likewise
        // drops a tensor's entry once the response is sent).
        if (ok && key.rfind("req/", 0) == 0 &&
            ++kv_.read_count[key] >= kv_.world) {
          kv_.data.erase(key);
          kv_.read_count.erase(key);
        }
        lk.unlock();
        Reply(fd, ok ? 0 : 1, out);
      } else if (op == 3) {  // BARRIER
        std::unique_lock<std::mutex> lk(kv_.mu);
        int gen = kv_.barrier_generation[key];
        if (++kv_.barrier_count[key] >= kv_.world) {
          kv_.barrier_count[key] = 0;
          kv_.barrier_generation[key] = gen + 1;
          lk.unlock();
          kv_.cv.notify_all();
          Reply(fd, 0, "");
        } else {
          bool ok = kv_.cv.wait_for(
              lk, std::chrono::milliseconds(atol(val.c_str())),
              [&] {
                return !running_ || kv_.barrier_generation[key] != gen;
              });
          ok = ok && kv_.barrier_generation[key] != gen;
          if (!ok && kv_.barrier_generation[key] == gen &&
              kv_.barrier_count[key] > 0) {
            // Timed out: withdraw this participant so a retry (or the
            // next use of the id) still needs `world` distinct arrivals.
            --kv_.barrier_count[key];
          }
          lk.unlock();
          Reply(fd, ok ? 0 : 1, "");
        }
      } else if (op == 4) {  // PING
        Reply(fd, 0, "pong");
      } else {
        break;
      }
    }
    ::close(fd);
    std::lock_guard<std::mutex> lk(threads_mu_);
    conn_fds_.erase(fd);
  }

  std::atomic<bool> running_{false};
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::mutex threads_mu_;
  std::vector<std::thread> conn_threads_;
  std::set<int> conn_fds_;
};

RendezvousServer& g_server = *new RendezvousServer;

class RendezvousClient {
 public:
  bool Connect(const std::string& host, int port, double timeout_s) {
    double deadline = NowSeconds() + timeout_s;
    while (NowSeconds() < deadline) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
        // Hostname: resolve via getaddrinfo (multi-node coordinators
        // are usually named, not dotted-quad).
        addrinfo hints{};
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        addrinfo* res = nullptr;
        if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
            res == nullptr) {
          ::close(fd_);
          fd_ = -1;
          std::this_thread::sleep_for(std::chrono::milliseconds(200));
          continue;
        }
        addr.sin_addr =
            reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
        ::freeaddrinfo(res);
      }
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    fd_ = -1;
    return false;
  }

  // Returns status (0 ok), fills reply.
  int Request(uint8_t op, const std::string& key, const std::string& val,
              std::string* reply) {
    std::lock_guard<std::mutex> lk(mu_);
    if (fd_ < 0) return 2;
    uint32_t payload = static_cast<uint32_t>(1 + 4 + key.size() + 4 +
                                             val.size());
    uint32_t len_n = htonl(payload);
    uint32_t klen_n = htonl(static_cast<uint32_t>(key.size()));
    uint32_t vlen_n = htonl(static_cast<uint32_t>(val.size()));
    if (!WriteFull(fd_, &len_n, 4) || !WriteFull(fd_, &op, 1) ||
        !WriteFull(fd_, &klen_n, 4) ||
        !WriteFull(fd_, key.data(), key.size()) ||
        !WriteFull(fd_, &vlen_n, 4) ||
        !WriteFull(fd_, val.data(), val.size()))
      return 2;
    uint32_t rlen_n;
    if (!ReadFull(fd_, &rlen_n, 4)) return 2;
    uint32_t rlen = ntohl(rlen_n);
    std::vector<char> buf(rlen);
    if (!ReadFull(fd_, buf.data(), rlen)) return 2;
    uint8_t status = static_cast<uint8_t>(buf[0]);
    uint32_t vlen = ntohl(*reinterpret_cast<uint32_t*>(&buf[1]));
    if (reply) reply->assign(&buf[5], vlen);
    return status;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
  }

 private:
  static bool ReadFull(int fd, void* buf, size_t n) {
    char* p = static_cast<char*>(buf);
    while (n) {
      ssize_t r = ::recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }
  static bool WriteFull(int fd, const void* buf, size_t n) {
    const char* p = static_cast<const char*>(buf);
    while (n) {
      ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  std::mutex mu_;
  int fd_ = -1;
};

RendezvousClient& g_client = *new RendezvousClient;

thread_local std::string g_last_error;

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// --- membership (mpi_ops.cc:1536-1563 parity) ---

int hvd_native_init(int rank, int size, int local_rank, int local_size) {
  g_member.rank = rank;
  g_member.size = size;
  g_member.local_rank = local_rank;
  g_member.local_size = local_size;
  g_member.initialized.store(true);
  return 0;
}

int hvd_native_rank() {
  return g_member.initialized.load() ? g_member.rank : -1;
}
int hvd_native_size() {
  return g_member.initialized.load() ? g_member.size : -1;
}
int hvd_native_local_rank() {
  return g_member.initialized.load() ? g_member.local_rank : -1;
}
int hvd_native_local_size() {
  return g_member.initialized.load() ? g_member.local_size : -1;
}

int hvd_native_shutdown() {
  g_member.initialized.store(false);
  g_member.rank = g_member.size = -1;
  g_member.local_rank = g_member.local_size = -1;
  g_stall.StopThread();
  g_timeline.Stop();
  return 0;
}

// --- validation (ConstructMPIResponse parity, mpi_ops.cc:266-474) ---
//
// dtypes: nranks C strings. shapes: flattened int64 dims; ndims[i] gives
// rank i's dim count. root_ranks: nranks ints or NULL. Returns 0 when
// consistent; 1 and writes a message into err (cap bytes) otherwise.

int hvd_native_validate(const char* name, const char* op, int nranks,
                        const char** dtypes, const int* ndims,
                        const long long* shapes, const int* root_ranks,
                        int allow_dim0_mismatch, char* err, int cap) {
  auto fail = [&](const std::string& msg) {
    if (err && cap > 0) std::snprintf(err, cap, "%s", msg.c_str());
    return 1;
  };
  (void)op;
  for (int r = 1; r < nranks; ++r) {
    if (std::strcmp(dtypes[r], dtypes[0]) != 0) {
      return fail(std::string("Mismatched data types: One or more ranks "
                              "submitted tensor ") + name +
                  " with dtype " + dtypes[r] + ", but rank 0 submitted "
                  "dtype " + dtypes[0] + ".");
    }
  }
  if (root_ranks) {
    for (int r = 1; r < nranks; ++r) {
      if (root_ranks[r] != root_ranks[0]) {
        return fail(std::string("Mismatched root ranks: One or more "
                                "ranks submitted tensor ") + name +
                    " with root rank " + std::to_string(root_ranks[r]) +
                    ", but rank 0 submitted root rank " +
                    std::to_string(root_ranks[0]) + ".");
      }
    }
  }
  std::vector<int> offset(nranks, 0);
  int acc = 0;
  for (int r = 0; r < nranks; ++r) {
    offset[r] = acc;
    acc += ndims[r];
  }
  for (int r = 1; r < nranks; ++r) {
    if (ndims[r] != ndims[0]) {
      return fail(std::string("Mismatched tensor ranks: tensor ") + name +
                  " has rank " + std::to_string(ndims[r]) + " on rank " +
                  std::to_string(r) + " but " + std::to_string(ndims[0]) +
                  " on rank 0.");
    }
    int start = allow_dim0_mismatch ? 1 : 0;
    for (int d = start; d < ndims[r]; ++d) {
      if (shapes[offset[r] + d] != shapes[offset[0] + d]) {
        std::string what =
            allow_dim0_mismatch ? "non-first dimensions" : "shapes";
        std::string s0 = "(", sr = "(";
        for (int k = 0; k < ndims[0]; ++k)
          s0 += std::to_string(shapes[offset[0] + k]) +
                (k + 1 < ndims[0] ? ", " : "");
        for (int k = 0; k < ndims[r]; ++k)
          sr += std::to_string(shapes[offset[r] + k]) +
                (k + 1 < ndims[r] ? ", " : "");
        if (ndims[0] == 1) s0 += ",";
        if (ndims[r] == 1) sr += ",";
        s0 += ")";
        sr += ")";
        return fail(std::string("Mismatched ") + what + ": tensor " +
                    name + " has shape " + sr + " on rank " +
                    std::to_string(r) + " but " + s0 + " on rank 0.");
      }
    }
  }
  return 0;
}

// --- timeline ---

int hvd_native_timeline_start(const char* path) {
  return g_timeline.Start(path) ? 0 : 1;
}
void hvd_native_timeline_record(const char* tensor, const char* phase,
                                const char* activity) {
  g_timeline.Record(tensor, phase, activity ? activity : "");
}
void hvd_native_timeline_mark(const char* tensor, const char* name) {
  g_timeline.Mark(tensor, name);
}
void hvd_native_timeline_stop() { g_timeline.Stop(); }

// --- stall detector ---

void hvd_native_stall_configure(double warning_s, double check_every_s) {
  g_stall.Configure(warning_s, check_every_s);
}
void hvd_native_stall_start_thread() { g_stall.StartThread(); }
void hvd_native_stall_stop_thread() { g_stall.StopThread(); }
void hvd_native_stall_begin(const char* name) { g_stall.Begin(name); }
void hvd_native_stall_end(const char* name) { g_stall.End(name); }
int hvd_native_stall_check(char* out, int cap) {
  return g_stall.Check(out, cap);
}

// --- rendezvous ---

int hvd_native_rendezvous_serve(int port, int world) {
  return g_server.Serve(port, world);
}
void hvd_native_rendezvous_stop() { g_server.Stop(); }

int hvd_native_client_connect(const char* host, int port,
                              double timeout_s) {
  return g_client.Connect(host, port, timeout_s) ? 0 : 1;
}
void hvd_native_client_close() { g_client.Close(); }

int hvd_native_kv_set(const char* key, const char* val, int vlen) {
  return g_client.Request(1, key, std::string(val, vlen), nullptr);
}

// Returns length of value (-1 on timeout/error); copies into out.
int hvd_native_kv_get(const char* key, long timeout_ms, char* out,
                      int cap) {
  std::string reply;
  int status = g_client.Request(2, key, std::to_string(timeout_ms), &reply);
  if (status != 0) return -1;
  int n = static_cast<int>(reply.size());
  if (out && cap > 0)
    std::memcpy(out, reply.data(),
                static_cast<size_t>(n < cap ? n : cap));
  return n;
}

int hvd_native_barrier(const char* id, long timeout_ms) {
  return g_client.Request(3, id, std::to_string(timeout_ms), nullptr);
}

int hvd_native_ping() {
  std::string reply;
  return g_client.Request(4, "", "", &reply) == 0 && reply == "pong" ? 0 : 1;
}

}  // extern "C"
