"""Lazy native build.

The reference compiles its C++ at pip-install time against TF headers
(`setup.py:264-337`); the TPU control plane has no framework header
dependency, so it compiles on first use with plain g++ and is cached next
to the source. A failed build degrades to the pure-Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "control_plane.cc")
_OUT = os.path.join(os.path.dirname(__file__), "libhorovod_tpu_core.so")


def build_if_needed() -> str:
    """Compile the control plane if the .so is missing or stale.
    Returns the library path; raises on compile failure."""
    if (os.path.exists(_OUT)
            and os.path.getmtime(_OUT) >= os.path.getmtime(_SRC)):
        return _OUT
    # Build into a temp file then atomically rename, so concurrent
    # processes (hvdrun workers) never load a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so",
                               dir=os.path.dirname(_OUT))
    os.close(fd)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _OUT)
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(
            f"native control plane build failed:\n{e.stderr}") from e
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return _OUT
