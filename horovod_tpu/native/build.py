"""Lazy native build.

The reference compiles its C++ at pip-install time against TF headers
(`setup.py:264-337`); the TPU control plane has no framework header
dependency, so it compiles on first use with plain g++ and is cached next
to the source. A failed build degrades to the pure-Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import tempfile

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "control_plane.cc")
_OUT = os.path.join(_DIR, "libhorovod_tpu_core.so")


def build_library(src: str, out: str) -> str:
    """Compile `src` into shared library `out` if missing or stale.
    Returns the library path; raises on compile failure."""
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    # Build into a temp file then atomically rename, so concurrent
    # processes (hvdrun workers) never load a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out))
    os.close(fd)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(
            f"native build of {os.path.basename(src)} failed:\n"
            f"{e.stderr}") from e
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out


def build_if_needed() -> str:
    """Compile the control plane if missing/stale."""
    return build_library(_SRC, _OUT)


def build_data_loader() -> str:
    """Compile the native data loader if missing/stale."""
    return build_library(os.path.join(_DIR, "data_loader.cc"),
                         os.path.join(_DIR, "libhorovod_tpu_data.so"))
