"""Native (C++) control plane loader.

Mirrors the reference's dual-load pattern (`horovod/tensorflow/mpi_ops.py:
43-77`): the compiled library is loaded via ctypes and exposes the C
control API. Built lazily with g++ on first use; a build failure degrades
gracefully to the pure-Python implementations (validation, timeline,
stall detection) so the framework never hard-fails on a missing toolchain.
"""

from __future__ import annotations


def load_native():
    from horovod_tpu.native.bindings import NativeControlPlane
    return NativeControlPlane.load()
