"""Vision Transformer (Dosovitskiy et al. 2021) — encoder model family.

No reference equivalent (Horovod v0.10 predates ViT; its benchmark
family is the tf_cnn_benchmarks CNNs) — this extends the model zoo with
the modern image backbone, built TPU-first from the same parallel
primitives as the flagship LM:

* **Patchify = space-to-depth + one Dense**: a [B,H,W,C] image becomes
  [B, (H/p)(W/p), p*p*C] with a reshape/transpose and projects through
  a single matmul — the entire "stem" is one MXU-shaped contraction
  (p=16, C=3 -> 768-wide), unlike a CNN stem's 3-channel conv
  (cf. `resnet.py::SpaceToDepthStem`, which has to re-pack a conv to
  get the same effect).
* **Encoder blocks are `TransformerBlock(causal=False)`** — the exact
  TP (Megatron column/row) attention+MLP blocks of the LM, so tensor
  parallelism over ``model`` and sequence parallelism over ``seq``
  (ring/ulysses/flash impls, bidirectional) compose unchanged.
* **bf16 activations, fp32 LayerNorm/head** — the standard TPU recipe.
* Global-average pooling head (no CLS token): keeps the token count at
  exactly (H/p)(W/p), which divides SP degrees and kernel block sizes.

Works with `make_cnn_train_step` (no BatchNorm state; the empty
batch_stats collection is handled) and `bench.py --model vit`.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

from horovod_tpu.models.transformer import TransformerBlock

Dtype = Any


class VisionTransformer(nn.Module):
    num_classes: int = 1000
    patch: int = 16
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    mlp_ratio: int = 4
    dtype: Optional[Dtype] = jnp.bfloat16
    attn_impl: str = "blockwise"
    # (no `window`: sliding windows are causal-only; per-step remat
    # lives in make_cnn_train_step(remat=True), which checkpoints the
    # whole forward)

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False):
        B, H, W, C = x.shape
        p = self.patch
        if H % p or W % p:
            raise ValueError(
                f"image size {(H, W)} must be divisible by patch {p}")
        d = self.num_heads * self.head_dim
        # Patchify: space-to-depth then one Dense (a single [p*p*C, d]
        # MXU contraction).
        x = x.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, (H // p) * (W // p), p * p * C)
        x = nn.Dense(d, dtype=self.dtype, name="patch_embed")(x)
        n_tokens = x.shape[1]
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (n_tokens, d), jnp.float32)
        x = (x + pos).astype(self.dtype)

        block = partial(TransformerBlock,
                        num_heads=self.num_heads,
                        head_dim=self.head_dim,
                        mlp_ratio=self.mlp_ratio,
                        dtype=self.dtype,
                        attn_impl=self.attn_impl,
                        causal=False)
        for i in range(self.num_layers):
            x = block(name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        x = x.mean(axis=1)  # global average pool over tokens
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


# ViT-S/16 and ViT-B/16 (Dosovitskiy et al. 2021, Table 1).
ViT_S16 = partial(VisionTransformer, num_layers=12, num_heads=6,
                  head_dim=64)
ViT_B16 = partial(VisionTransformer, num_layers=12, num_heads=12,
                  head_dim=64)
