"""MNIST convnet.

Same architecture as the reference examples' model
(`examples/tensorflow_mnist.py:24-48` / `examples/keras_mnist.py:54-64`:
conv5x5(32) → maxpool → conv5x5(64) → maxpool → dense → dropout →
dense(10)), expressed TPU-first: NHWC, channels padded to MXU-friendly
sizes by XLA, bfloat16 compute optional.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
