"""Inception V3 — the second headline benchmark model.

The reference reports 90 % scaling efficiency for Inception V3 at 128
GPUs (`README.md:27-32`); BASELINE.md carries images/sec/chip for it as
a target metric. Structure follows Szegedy et al. 2015 (the
tf_cnn_benchmarks version the reference benchmarked): stem → 3×
InceptionA → InceptionB → 4× InceptionC → InceptionD → 2× InceptionE →
global pool → logits. Aux head omitted (benchmarks run without it).

TPU notes: every branch is 1x1/3x3/5x5(as double-3x3)/pool convs in
NHWC — all MXU-friendly; branch concat on the channel axis fuses cleanly
under XLA.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.resnet import space_to_depth


class _S2DStemConv(nn.Module):
    """The 3×3/s2/VALID stem conv computed space-to-depth — the
    Inception analogue of `resnet.SpaceToDepthStem` (docs/mfu.md
    culprit #1: a C_in=3 contraction fills ~3/128 MXU lanes).

    Cleaner than ResNet's 7×7 case because stride (2) equals the s2d
    block: pad the image so the width is `2·(out+1)`, space-to-depth by
    2 ([N,H',W',C] → [N,H'/2,W'/2,4C]), and convolve with the SAME
    [3,3,C,F] parameter re-packed into [2,2,4C,F] (zero-pad the kernel
    to 4×4 first; tap (2U+du, 2V+dv) lands at s2d position (U,V),
    channel (du·2+dv)·C+c), stride 1, VALID — no depth-to-space needed.
    Extra zero pad columns multiply zeros in both formulations, so the
    equality is exact. Declares the same `kernel` parameter as nn.Conv
    under the same name, so `s2d_stem` stays a pure compute-path flag.
    """
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        N, H, W, C = x.shape
        F = self.features
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (3, 3, C, F))
        out_h = (H - 3) // 2 + 1
        out_w = (W - 3) // 2 + 1
        x = jnp.pad(x, ((0, 0), (0, 2 * (out_h + 1) - H),
                        (0, 2 * (out_w + 1) - W), (0, 0)))
        # Shared packing convention with the ResNet stem — the kernel
        # re-pack below depends on exactly this (row, col, channel)
        # order.
        x = space_to_depth(x, 2).astype(self.dtype)

        k = kernel.astype(self.dtype)
        k4 = jnp.zeros((4, 4, C, F), k.dtype).at[:3, :3].set(k)
        w = (k4.reshape(2, 2, 2, 2, C, F)
             .transpose(0, 2, 1, 3, 4, 5).reshape(2, 2, 4 * C, F))
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert y.shape[1:3] == (out_h, out_w), y.shape
        return y


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: jnp.dtype = jnp.bfloat16
    train: bool = False
    s2d: bool = False   # stem-conv-only: see _S2DStemConv
    bn_sample: int = 1  # >1: sampled BN statistics (models.resnet)

    @nn.compact
    def __call__(self, x):
        if self.s2d:
            if (self.kernel, self.strides, self.padding) != (
                    (3, 3), (2, 2), "VALID"):
                raise ValueError(
                    "s2d applies to the 3x3/s2/VALID stem conv only")
            x = _S2DStemConv(self.features, dtype=self.dtype,
                             name="Conv_0")(x)
        else:
            x = nn.Conv(self.features, self.kernel, self.strides,
                        padding=self.padding, use_bias=False,
                        dtype=self.dtype, name="Conv_0")(x)
        if self.bn_sample > 1:
            from horovod_tpu.models.resnet import SampledBatchNorm
            x = SampledBatchNorm(use_running_average=not self.train,
                                 momentum=0.9, epsilon=1e-3,
                                 dtype=self.dtype,
                                 sample=self.bn_sample)(x)
        else:
            x = nn.BatchNorm(use_running_average=not self.train,
                             momentum=0.9, epsilon=1e-3,
                             dtype=self.dtype)(x)
        return nn.relu(x)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16
    # MXU-friendly stem conv0 (same params, same outputs): _S2DStemConv
    s2d_stem: bool = False
    bn_sample: int = 1  # >1: sampled BN statistics (models.resnet)

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(ConvBN, dtype=self.dtype, train=train,
                       bn_sample=self.bn_sample)
        x = x.astype(self.dtype)
        # Stem: 299x299x3 -> 35x35x192
        x = conv(32, (3, 3), (2, 2), padding="VALID",
                 s2d=self.s2d_stem)(x)
        x = conv(32, (3, 3), padding="VALID")(x)
        x = conv(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, (1, 1), padding="VALID")(x)
        x = conv(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))

        def inception_a(x, pool_features):
            b1 = conv(64, (1, 1))(x)
            b2 = conv(48, (1, 1))(x)
            b2 = conv(64, (5, 5))(b2)
            b3 = conv(64, (1, 1))(x)
            b3 = conv(96, (3, 3))(b3)
            b3 = conv(96, (3, 3))(b3)
            b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            b4 = conv(pool_features, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

        def inception_b(x):
            b1 = conv(384, (3, 3), (2, 2), padding="VALID")(x)
            b2 = conv(64, (1, 1))(x)
            b2 = conv(96, (3, 3))(b2)
            b2 = conv(96, (3, 3), (2, 2), padding="VALID")(b2)
            b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b1, b2, b3], axis=-1)

        def inception_c(x, c7):
            b1 = conv(192, (1, 1))(x)
            b2 = conv(c7, (1, 1))(x)
            b2 = conv(c7, (1, 7))(b2)
            b2 = conv(192, (7, 1))(b2)
            b3 = conv(c7, (1, 1))(x)
            b3 = conv(c7, (7, 1))(b3)
            b3 = conv(c7, (1, 7))(b3)
            b3 = conv(c7, (7, 1))(b3)
            b3 = conv(192, (1, 7))(b3)
            b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            b4 = conv(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

        def inception_d(x):
            b1 = conv(192, (1, 1))(x)
            b1 = conv(320, (3, 3), (2, 2), padding="VALID")(b1)
            b2 = conv(192, (1, 1))(x)
            b2 = conv(192, (1, 7))(b2)
            b2 = conv(192, (7, 1))(b2)
            b2 = conv(192, (3, 3), (2, 2), padding="VALID")(b2)
            b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b1, b2, b3], axis=-1)

        def inception_e(x):
            b1 = conv(320, (1, 1))(x)
            b2 = conv(384, (1, 1))(x)
            b2 = jnp.concatenate([conv(384, (1, 3))(b2),
                                  conv(384, (3, 1))(b2)], axis=-1)
            b3 = conv(448, (1, 1))(x)
            b3 = conv(384, (3, 3))(b3)
            b3 = jnp.concatenate([conv(384, (1, 3))(b3),
                                  conv(384, (3, 1))(b3)], axis=-1)
            b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            b4 = conv(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

        x = inception_a(x, 32)
        x = inception_a(x, 64)
        x = inception_a(x, 64)
        x = inception_b(x)
        x = inception_c(x, 128)
        x = inception_c(x, 160)
        x = inception_c(x, 160)
        x = inception_c(x, 192)
        x = inception_d(x)
        x = inception_e(x)
        x = inception_e(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
