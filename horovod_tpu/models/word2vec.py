"""Word2vec skip-gram — the sparse-gradient exercise.

The reference's `examples/tensorflow_word2vec.py` exists to exercise the
IndexedSlices → allgather path (`horovod/tensorflow/__init__.py:61-72`,
SURVEY §3.4): embedding-lookup gradients touch only the looked-up rows,
so allreducing them densely wastes bandwidth. This model reproduces that
shape: skip-gram with NCE-style sampled logits; `sparse_grads()` returns
the embedding gradient as `IndexedSlices` for the sparse collective path.

TPU note: the lookup is `take(..., axis=0)` (gather) and the sparse
update is a `scatter-add`; both lower to efficient TPU HLOs, and the
gathered (values, indices) ride one `all_gather` over ICI.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.ops.sparse import IndexedSlices


class Word2Vec(nn.Module):
    vocab_size: int = 50000
    embed_dim: int = 128

    @nn.compact
    def __call__(self, center: jax.Array, context: jax.Array,
                 negative: jax.Array):
        """center/context: [B] int ids; negative: [B, K] sampled ids.
        Returns the NCE-style loss."""
        emb = self.param("embeddings",
                         nn.initializers.uniform(scale=1.0),
                         (self.vocab_size, self.embed_dim))
        out = self.param("nce_weights",
                         nn.initializers.truncated_normal(
                             stddev=1.0 / self.embed_dim ** 0.5),
                         (self.vocab_size, self.embed_dim))
        v = jnp.take(emb, center, axis=0)               # [B, D]
        u_pos = jnp.take(out, context, axis=0)          # [B, D]
        u_neg = jnp.take(out, negative, axis=0)         # [B, K, D]
        pos_logit = jnp.sum(v * u_pos, axis=-1)         # [B]
        neg_logit = jnp.einsum("bd,bkd->bk", v, u_neg)  # [B, K]
        loss = (-jax.nn.log_sigmoid(pos_logit)
                - jax.nn.log_sigmoid(-neg_logit).sum(axis=-1))
        return loss.mean()


def embedding_grad_as_slices(dense_grad: jax.Array,
                             touched_ids: jax.Array) -> IndexedSlices:
    """Convert a dense embedding-table gradient into IndexedSlices over
    the touched rows — the JAX analogue of TF returning IndexedSlices
    from an embedding lookup's backward pass."""
    # Pad slots must not duplicate a real row's gradient: mark them with
    # -1, gather through a safe index, and zero their values so
    # to_dense()'s scatter-add is exact even with duplicate ids.
    ids = jnp.unique(touched_ids.ravel(), size=touched_ids.size,
                     fill_value=-1)
    valid = ids >= 0
    safe_ids = jnp.where(valid, ids, 0)
    values = jnp.take(dense_grad, safe_ids, axis=0)
    values = values * valid[:, None].astype(values.dtype)
    return IndexedSlices(values, safe_ids,
                         dense_shape=tuple(dense_grad.shape))
