"""BERT-style masked-LM (Devlin et al. 2019) — encoder pretraining.

No reference equivalent (Horovod v0.10 predates BERT; SURVEY §2.3 —
its model surface is the tf_cnn_benchmarks CNNs). This completes the
model zoo's pretraining objectives: causal LM (`TransformerLM`),
image classification (CNNs/`VisionTransformer`), embeddings
(`word2vec`), and now bidirectional masked-LM — all on the SAME
TP/SP-composable `TransformerBlock`s, so every parallelism axis and
attention kernel of the flagship LM applies unchanged
(`causal=False`, like the ViT encoder).

TPU notes:
* The MLM loss reduces ONLY masked positions, but as a dense
  `where`-weighted cross entropy over the full [B, S] grid — no
  gather/dynamic shapes, so XLA keeps one static program and the MXU
  sees the full [B*S, d] @ [d, V] head matmul (masked rows are free
  relative to a ragged gather on TPU).
* Tied embedding/head, vocab shardable over ``model`` exactly like
  `TransformerLM`'s (the `nn.with_partitioning` annotation).
* `make_mlm_batch` implements the standard 80/10/10 corruption rule
  as pure jax (jit/vmap-safe, one PRNG key in).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

import flax.linen as nn

from horovod_tpu.models.transformer import TransformerBlock
from horovod_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ, constrain

Dtype = Any


class BertMLM(nn.Module):
    """Bidirectional encoder with a tied masked-LM head.

    Input [B, S] int tokens -> [B, S, V] logits (every position; the
    loss selects masked ones). ``segment_ids`` (optional [B, S] in
    {0, 1}) adds the sentence-pair embedding of the original
    pretraining setup.
    """

    vocab_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    max_len: int = 512
    mlp_ratio: int = 4
    num_segments: int = 2
    dtype: Optional[Dtype] = jnp.bfloat16
    attn_impl: str = "blockwise"

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 segment_ids: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> Any:
        B, S = tokens.shape
        d = self.num_heads * self.head_dim
        embed = self.param(
            "embed",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 (AXIS_MODEL, None)),
            (self.vocab_size, d), jnp.float32)
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (self.max_len, d), jnp.float32)
        x = jnp.take(embed, tokens, axis=0) + pos[:S]
        if segment_ids is not None:
            seg = self.param("segment", nn.initializers.normal(0.02),
                             (self.num_segments, d), jnp.float32)
            x = x + jnp.take(seg, segment_ids, axis=0)
        x = x.astype(self.dtype)
        x = constrain(x, AXIS_DATA, AXIS_SEQ, None)

        block = partial(TransformerBlock,
                        num_heads=self.num_heads,
                        head_dim=self.head_dim,
                        mlp_ratio=self.mlp_ratio,
                        dtype=self.dtype,
                        attn_impl=self.attn_impl,
                        causal=False)
        for i in range(self.num_layers):
            x = block(name=f"block_{i}")(x)
            x = constrain(x, AXIS_DATA, AXIS_SEQ, None)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        if return_hidden:
            # For the chunked fused head+loss (`chunked_mlm_loss`):
            # the [B, S, V] logits never materialize.
            return x, embed
        # Tied MLM head (the BERT transform layer folded away: one
        # matmul against the embedding — vocab sharded over `model`).
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(self.dtype))
        return constrain(logits, AXIS_DATA, AXIS_SEQ, AXIS_MODEL)


def make_mlm_batch(rng: jax.Array, tokens: jax.Array, *,
                   vocab_size: int, mask_id: int,
                   mask_rate: float = 0.15
                   ) -> Tuple[jax.Array, jax.Array]:
    """The 80/10/10 corruption rule, dense and jit-safe.

    Selects ~``mask_rate`` of positions; of those, 80 % become
    ``mask_id``, 10 % a uniform random token, 10 % stay themselves.
    Returns ``(corrupted_tokens, is_target [B, S] bool)`` — the loss
    reduces over ``is_target`` (which marks ALL selected positions,
    including the kept ones, per the paper).
    """
    k_sel, k_op, k_rand = jax.random.split(rng, 3)
    sel = jax.random.uniform(k_sel, tokens.shape) < mask_rate
    op = jax.random.uniform(k_op, tokens.shape)
    rand_tok = jax.random.randint(k_rand, tokens.shape, 0, vocab_size)
    corrupted = jnp.where(op < 0.8, mask_id,
                          jnp.where(op < 0.9, rand_tok, tokens))
    return jnp.where(sel, corrupted, tokens), sel


def mlm_loss(logits: jax.Array, targets: jax.Array,
             is_target: jax.Array) -> jax.Array:
    """Masked cross entropy: mean over target positions only, computed
    densely (a `where` weight, no gather) so the program stays static
    for XLA — the zoo's shared CE numerics (optax). ``targets`` are
    the ORIGINAL tokens."""
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets)
    w = is_target.astype(jnp.float32)
    return (ce * w).sum() / jnp.maximum(w.sum(), 1.0)


def chunked_mlm_loss(hidden: jax.Array, embed: jax.Array,
                     targets: jax.Array, is_target: jax.Array, *,
                     chunk: int = 512) -> jax.Array:
    """Masked CE fused with the MLM head, scanned over sequence chunks
    so the [B, S, V] logits never materialize — the MLM analogue of
    `transformer.chunked_lm_loss`, sharing its `chunked_weighted_ce`
    core (`jax.checkpoint` recomputes each chunk's logits in the
    backward; 1 GiB of bf16 logits at B32·S512·V32k drops to
    1/(S/chunk)). Composes with dp (batch must divide the ``data``
    axis — a ragged batch can trip an XLA partitioner CHECK inside
    the scan, same as the LM loss); with sequence parallelism keep
    the plain `mlm_loss`."""
    from horovod_tpu.models.transformer import chunked_weighted_ce

    w = is_target.astype(jnp.float32)
    total = chunked_weighted_ce(hidden, embed, targets, w, chunk=chunk)
    return total / jnp.maximum(w.sum(), 1.0)


def make_mlm_train_step(model: BertMLM, tx, mesh, *,
                        mask_id: Optional[int] = None,
                        mask_rate: float = 0.15,
                        loss_chunk: Optional[int] = None):
    """Jitted MLM pretraining step over the mesh: corrupt -> forward ->
    masked CE -> grads (GSPMD inserts the DP psum / TP collectives from
    the shardings, exactly as in `make_lm_train_step`).

    ``mask_id`` defaults to the LAST vocab id — fine for synthetic
    corpora; a real tokenizer should pass its dedicated [MASK] id so
    genuine occurrences of the last token are not conflated with
    masked positions. ``mask_rate`` is the paper's 15 % by default.
    ``loss_chunk``: compute the masked CE via `chunked_mlm_loss`
    (the [B, S, V] logits never materialize).
    """
    from horovod_tpu.parallel.mesh import use
    from horovod_tpu.parallel.tensor import unbox

    mid = model.vocab_size - 1 if mask_id is None else mask_id

    def step(params, opt_state, tokens, rng):
        def loss_fn(p):
            corrupted, sel = make_mlm_batch(
                rng, tokens, vocab_size=model.vocab_size,
                mask_id=mid, mask_rate=mask_rate)
            if loss_chunk:
                hidden, embed = model.apply(
                    {"params": p}, corrupted, return_hidden=True)
                return chunked_mlm_loss(hidden, embed, tokens, sel,
                                        chunk=loss_chunk)
            logits = model.apply({"params": p}, corrupted)
            return mlm_loss(logits, tokens, sel)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Donate params/opt_state like make_lm_train_step: the old state
    # buffers are dead after the update — without donation BERT-Large
    # + Adam would hold both generations live every step.
    jitted = jax.jit(step, donate_argnums=(0, 1))

    def run(params, opt_state, tokens, rng):
        with use(mesh):
            return jitted(params, opt_state, tokens, rng)
    return run


# BERT-Base / BERT-Large (Devlin et al. 2019).
BertBase = partial(BertMLM, num_layers=12, num_heads=12, head_dim=64)
BertLarge = partial(BertMLM, num_layers=24, num_heads=16, head_dim=64)
