"""ResNet-50/101/152 — the flagship benchmark family.

The reference's headline number is 90 % scaling efficiency for ResNet-101
data-parallel training on 128 GPUs (`README.md:27-32`, BASELINE.md); this
is the TPU-first implementation used by `bench.py` and
`__graft_entry__.py`.

TPU design notes:
* NHWC layout, 3x3/1x1 convs — XLA tiles these directly onto the MXU.
* bfloat16 activations/weights with float32 BatchNorm statistics and
  float32 final logits: the standard TPU mixed-precision recipe.
* Per-replica (local) BatchNorm, matching the reference's pure-DP
  semantics (no cross-replica stat sync in Horovod v0.10); a `sync_bn`
  flag adds cross-replica mean/var psum as a TPU-native extension
  (axis name "data") for small per-device batches.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


def space_to_depth(x, b):
    """[N, H, W, C] -> [N, H/b, W/b, b*b*C]; channel packing is
    (row-in-block, col-in-block, channel) — the convention the kernel
    re-packs in `SpaceToDepthStem` and `inception._S2DStemConv` depend
    on (shared helper, public on purpose)."""
    N, H, W, C = x.shape
    x = x.reshape(N, H // b, b, W // b, b, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        N, H // b, W // b, b * b * C)


class SpaceToDepthStem(nn.Module):
    """The ResNet 7x7/s2 stem computed as a space-to-depth conv — the
    standard MLPerf TPU trick for MXU underfill at the input layer.

    The plain stem convolves [N,H,W,3] with a [7,7,3,F] kernel: a
    3-channel contraction fills ~3/128 of an MXU pass, so the stem's
    ~25 % share of early FLOPs runs at a few percent efficiency
    (docs/mfu.md culprit #1). Here the image is 4x4 space-to-depth'd
    to [N,H/4,W/4,48] and convolved with a [3,3,48,4F] re-pack of the
    SAME [7,7,3,F] parameter (stride 1, VALID), then a 2x2
    depth-to-space restores [N,H/2,W/2,F] — numerically identical to
    the plain stem (oracle: tests/test_models.py) with a 16x larger
    contraction dim.

    The parameter tree is exactly nn.Conv's ({"kernel": [7,7,C,F]})
    under the same module name, so `s2d_stem` is a pure compute-path
    flag: checkpoints and inits are interchangeable with the plain
    stem.

    Derivation (1-D, per output column p = 2P + a, a in {0,1}): the
    SAME-padded stride-2 conv reads original pixels 2p-2+u, u in
    [0,7). With the image zero-padded by (2, 6) the window for s2d
    cell P starts at padded pixel 4P and spans 12 pixels = 3 cells;
    sub-position a selects kernel taps w[4U+du-2a], which is the
    [7,7] kernel embedded at offset (2a, 2b) in a [12,12] zero block.
    The extra trailing zero-pad columns (6 vs SAME's 3) multiply
    zeros in both formulations, so equality is exact.
    """
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        N, H, W, C = x.shape
        if H % 4 or W % 4:
            raise ValueError(
                f"space-to-depth stem needs H, W divisible by 4, got "
                f"{(H, W)}; use s2d_stem=False for this input")
        F = self.features
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, C, F))
        x = jnp.pad(x, ((0, 0), (2, 6), (2, 6), (0, 0)))
        x = space_to_depth(x, 4).astype(self.dtype)

        k = kernel.astype(self.dtype)
        taps = []
        for a in (0, 1):
            for b in (0, 1):
                kab = jnp.zeros((12, 12, C, F), k.dtype)
                kab = kab.at[2 * a:2 * a + 7, 2 * b:2 * b + 7].set(k)
                taps.append(
                    kab.reshape(3, 4, 3, 4, C, F)
                    .transpose(0, 2, 1, 3, 4, 5)
                    .reshape(3, 3, 16 * C, F))
        # Output packing o*4 + a*2 + b — undone by the depth-to-space
        # below.
        w = jnp.stack(taps, axis=-1).reshape(3, 3, 16 * C, 4 * F)

        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        P, Q = y.shape[1], y.shape[2]
        y = y.reshape(N, P, Q, F, 2, 2)
        return y.transpose(0, 1, 4, 2, 5, 3).reshape(
            N, 2 * P, 2 * Q, F)


class SampledBatchNorm(nn.Module):
    """BatchNorm whose train-time statistics come from a 1/``sample``
    slice of the batch (ghost-batch-style sampled statistics).

    Why: the r4 device profile measured BatchNorm statistics at 37.8 %
    of the ResNet-101 step (docs/mfu.md) — every feature map is
    re-read for the fwd mean/var and again for the bwd channel sums,
    and a reduction cannot fuse into the producing conv's epilogue
    under XLA. Computing statistics over ``batch[: B/sample]`` cuts
    that reduction traffic by ``sample`` in BOTH directions (autodiff
    pulls only the sampled rows through the stat grads) while the
    normalization itself — elementwise, fused into neighboring ops —
    still covers the full batch.

    ``sample=1`` is exact BatchNorm (oracle-tested against
    `nn.BatchNorm`); ``sample>1`` estimates the same statistics from
    fewer rows — the ghost-batch-normalization family (Hoffer et al.
    2017), here used for bandwidth rather than regularization. Eval
    (``use_running_average=True``) semantics are unchanged. The
    variable collections mirror `nn.BatchNorm` (params scale/bias,
    batch_stats mean/var); ``axis_name`` syncs sampled stats
    cross-replica exactly like `nn.BatchNorm` does (pmean of mean and
    mean-of-squares).
    """

    use_running_average: bool
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None
    axis_name: Optional[str] = None
    sample: int = 4
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        scale = self.param("scale", self.scale_init, (C,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (C,),
                          jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            n = max(1, x.shape[0] // max(1, self.sample))
            xs = lax.slice_in_dim(x, 0, n, axis=0)
            xs = xs.astype(jnp.float32)
            axes = tuple(range(xs.ndim - 1))
            mean = xs.mean(axes)
            mean2 = (xs * xs).mean(axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = jnp.maximum(mean2 - mean * mean, 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        inv = lax.rsqrt(var + self.epsilon) * scale
        y = (x.astype(jnp.float32) - mean) * inv + bias
        return y.astype(self.dtype or x.dtype)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale per the bag-of-tricks recipe: the
        # block starts as identity, which also speeds large-batch DP
        # training (Goyal et al. 2017 — the same paper the reference's
        # LR-warmup callback implements, horovod/keras/callbacks.py:89).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    sync_bn: bool = False
    axis_name: str = "data"
    # MXU-friendly stem (SpaceToDepthStem): same parameters, same
    # outputs, 16x larger stem contraction dim. Off by default so the
    # benchmark measures plain vs s2d explicitly (bench.py --stem).
    s2d_stem: bool = False
    # >1: train-time BN statistics from batch[: B/bn_sample]
    # (SampledBatchNorm) — attacks the measured 37.8 %-of-step BN stat
    # traffic (docs/mfu.md). 1 = exact nn.BatchNorm. The choice is a
    # model-config constant (not train-flag-dependent) so train and
    # eval share one variable tree.
    bn_sample: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype)
        bn_axis = self.axis_name if (self.sync_bn and train) else None
        if self.bn_sample > 1:
            norm = partial(SampledBatchNorm,
                           use_running_average=not train,
                           momentum=0.9, epsilon=1e-5,
                           dtype=self.dtype, axis_name=bn_axis,
                           sample=self.bn_sample)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           axis_name=bn_axis)

        x = x.astype(self.dtype)
        if self.s2d_stem:
            x = SpaceToDepthStem(self.width, dtype=self.dtype,
                                 name="stem_conv")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.width * 2 ** i, strides,
                                    conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
