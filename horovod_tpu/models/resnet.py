"""ResNet-50/101/152 — the flagship benchmark family.

The reference's headline number is 90 % scaling efficiency for ResNet-101
data-parallel training on 128 GPUs (`README.md:27-32`, BASELINE.md); this
is the TPU-first implementation used by `bench.py` and
`__graft_entry__.py`.

TPU design notes:
* NHWC layout, 3x3/1x1 convs — XLA tiles these directly onto the MXU.
* bfloat16 activations/weights with float32 BatchNorm statistics and
  float32 final logits: the standard TPU mixed-precision recipe.
* Per-replica (local) BatchNorm, matching the reference's pure-DP
  semantics (no cross-replica stat sync in Horovod v0.10); a `sync_bn`
  flag adds cross-replica mean/var psum as a TPU-native extension
  (axis name "data") for small per-device batches.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale per the bag-of-tricks recipe: the
        # block starts as identity, which also speeds large-batch DP
        # training (Goyal et al. 2017 — the same paper the reference's
        # LR-warmup callback implements, horovod/keras/callbacks.py:89).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    sync_bn: bool = False
    axis_name: str = "data"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype)
        bn_axis = self.axis_name if (self.sync_bn and train) else None
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       axis_name=bn_axis)

        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), (2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.width * 2 ** i, strides,
                                    conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
