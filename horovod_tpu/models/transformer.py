"""Flagship model: GPT-style transformer LM over the full 5-axis mesh.

No reference equivalent — Horovod v0.10 ships no model library and no
attention (SURVEY §5.7); its largest exercised model family is the
tf_cnn_benchmarks CNNs. This is the TPU-native extension that makes the
brief's long-context + multi-axis parallelism first-class, composing
every `horovod_tpu.parallel` primitive in one model:

* **TP**: `ParallelSelfAttention` / `ParallelMLP` (Megatron column/row
  pairs, heads sharded over ``model``) — one all-reduce per sub-block,
  inserted by GSPMD, riding the innermost ICI axis.
* **SP**: `attn_impl="ring"` / `"ulysses"` run the attention as a
  shard_map region over the ``seq`` axis (K/V `ppermute` ring or
  all-to-all head swap).
* **EP**: `moe_every=n` replaces every n-th MLP with a GShard-style
  `MoELayer`, experts sharded over ``expert``.
* **DP**: the train step shards the batch over ``data``; since params
  carry no ``data`` axis, GSPMD inserts the gradient all-reduce —
  the reference's entire product (`DistributedOptimizer`,
  `horovod/tensorflow/__init__.py:127-186`) falls out of the sharding.
* **PP**: `TransformerBlockStack` exposes the per-block apply used by
  `parallel.pipeline.pipeline_apply_gspmd` (GPipe over ``pipe``).

Attention kernels: ``dot`` (materialized softmax baseline), ``blockwise``
(online-softmax scan), ``flash`` (Pallas TPU kernel,
`ops/flash_attention.py`), ``ring``/``ulysses`` (sequence-parallel).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import flax.linen as nn

from horovod_tpu.annotations import hot_path
from horovod_tpu.parallel.expert import MoELayer
from horovod_tpu.parallel.mesh import (
    AXIS_DATA, AXIS_MODEL, AXIS_SEQ, constrain, use,
)
from horovod_tpu.parallel.sequence import (
    banded_causal_mask, blockwise_attention, ring_attention_gspmd,
    ulysses_attention_gspmd,
)
from horovod_tpu.parallel.tensor import (
    ParallelMLP, ParallelSelfAttention, ParallelSwiGLU,
    dot_product_attention,
    param_specs, shard_params, unbox,
)

Dtype = Any

ATTN_IMPLS = ("dot", "blockwise", "flash", "ring", "ring_flash",
              "ulysses", "ulysses_flash")

# The LLaMA-family knob set — single source for `compat.hf.from_hf_llama`,
# `bench.py --arch llama`, and the driver dryrun's llama leg, so the
# three can never silently diverge.
LLAMA_ARCH_KW = dict(norm="rmsnorm", mlp_impl="swiglu",
                     tied_head=False)


def make_attn_fn(impl: str, *, causal: bool = True,
                 block_size: int = 512,
                 window: Optional[int] = None,
                 flash_block_q: int = 128,
                 flash_block_k: int = 128) -> Optional[Callable]:
    """attn_fn for `ParallelSelfAttention` (None = dot baseline, which
    consumes the explicit mask argument instead). ``window`` = sliding
    -window attention (last `window` positions only; requires causal).
    ``flash_block_q``/``flash_block_k``: Pallas kernel grid tile sizes
    (``impl="flash"``) — the VMEM-vs-grid-steps trade is shape- and
    generation-dependent, so `bench.py --flash-block-q/-k` sweeps them
    on hardware; defaults match the kernel's.
    """
    from horovod_tpu.parallel.sequence import check_window
    check_window(window)
    if (flash_block_q, flash_block_k) != (128, 128) and impl != "flash":
        # ring_flash/ulysses_flash run the kernel at its defaults (the
        # per-shard sequences are already small); silently ignoring the
        # knob would make a hardware sweep measure identical kernels.
        raise ValueError(
            f"flash_block_q/flash_block_k apply to attn_impl='flash' "
            f"only (got impl={impl!r})")
    if impl == "dot":
        return None

    def _no_mask(m):
        if m is not None:
            raise NotImplementedError(
                f"attn_impl={impl!r} supports causal masking only; use "
                f"impl='dot' for arbitrary masks")

    if impl == "blockwise":
        def attn(q, k, v, m):
            _no_mask(m)
            return blockwise_attention(q, k, v, causal=causal,
                                       window=window,
                                       block_size=block_size)
        return attn
    if impl == "flash":
        from horovod_tpu.ops.flash_attention import flash_attention

        def attn(q, k, v, m):
            _no_mask(m)
            return flash_attention(q, k, v, causal=causal,
                                   window=window,
                                   block_q=flash_block_q,
                                   block_k=flash_block_k)
        # The kernel consumes grouped K/V natively (index-mapped kv
        # heads); let ParallelSelfAttention skip the repeat.
        attn.native_gqa = True
        return attn
    if impl in ("ring", "ring_flash", "ulysses", "ulysses_flash"):
        if impl == "ulysses":
            sp_fn = ulysses_attention_gspmd
        elif impl == "ulysses_flash":
            # Local attention after the head-swap all_to_alls is the
            # Pallas flash kernel instead of the blockwise scan.
            from horovod_tpu.ops.flash_attention import flash_attention
            sp_fn = functools.partial(ulysses_attention_gspmd,
                                      attn_impl=flash_attention)
        elif impl == "ring_flash":
            # Pallas flash kernel on every ring rotation; partials
            # merge by logsumexp (sequence._ring_attention_flash).
            sp_fn = functools.partial(ring_attention_gspmd,
                                      block_impl="flash")
        else:
            sp_fn = ring_attention_gspmd

        native_gqa = impl in ("ring_flash", "ulysses_flash")

        def attn(q, k, v, m):
            _no_mask(m)
            # Off-mesh (e.g. model.init, single-device eval) there is no
            # seq axis to ring over; blockwise is the same math locally
            # and attention has no params, so the init trace is identical.
            from horovod_tpu.parallel.mesh import abstract_mesh
            mesh = abstract_mesh()
            if mesh is None or mesh.empty:
                if native_gqa and k.shape[2] != q.shape[2]:
                    # The flash paths take grouped K/V natively; the
                    # blockwise fallback needs the repeat inline.
                    g = q.shape[2] // k.shape[2]
                    k = jnp.repeat(k, g, axis=2)
                    v = jnp.repeat(v, g, axis=2)
                return blockwise_attention(q, k, v, causal=causal,
                                           window=window,
                                           block_size=block_size)
            return sp_fn(None, q, k, v, causal=causal, window=window)

        # K/V stay at kv-head width through the ppermute hops /
        # all_to_alls — 1/group the ICI payload (the kernel index-maps
        # kv heads; see flash_attention.native_gqa).
        attn.native_gqa = native_gqa
        return attn
    raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, got {impl!r}")


def _make_norm(kind: str, dtype, eps: float, name: str):
    """The block's norm: LayerNorm (GPT family) or RMSNorm (LLaMA
    family — scale only, no bias/mean-centering)."""
    if kind == "layernorm":
        return nn.LayerNorm(dtype=dtype, epsilon=eps, name=name)
    if kind == "rmsnorm":
        return nn.RMSNorm(dtype=dtype, epsilon=eps, name=name)
    raise ValueError(f"norm must be layernorm|rmsnorm, got {kind!r}")


class TransformerBlock(nn.Module):
    """Pre-LN transformer block: TP attention + TP MLP (or EP MoE)."""

    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None
    pos_emb: str = "none"        # "none" | "rope"
    rope_theta: float = 10000.0
    window: Optional[int] = None  # sliding-window attention
    mlp_ratio: int = 4
    dtype: Optional[Dtype] = jnp.bfloat16
    attn_impl: str = "blockwise"
    moe: bool = False
    num_experts: int = 8
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    decode: bool = False
    chunked_prefill: bool = False   # see ParallelSelfAttention
    # Linear-cache decode reads the filled prefix in slices this big
    # (see ParallelSelfAttention.decode_prefix_block); 0/None = the
    # cache-wide-mask path.
    decode_prefix_block: Optional[int] = 256
    decode_prefix_impl: str = "lax"   # "lax" | "pallas" (flash-decode)
    causal: bool = True     # False = bidirectional (encoder / ViT)
    weight_quant: Optional[str] = None   # None | "int8" (block matmuls)
    kv_quant: Optional[str] = None       # None | "int8" (decode cache)
    flash_block_q: int = 128             # Pallas flash tile sizes
    flash_block_k: int = 128
    attn_bias: bool = False              # GPT-2-family checkpoints
    attn_out_bias: Optional[bool] = None  # None = follow attn_bias
    ln_eps: float = 1e-6
    norm: str = "layernorm"              # "layernorm" | "rmsnorm"
    mlp_impl: str = "gelu"               # "gelu" | "swiglu" (LLaMA)
    mlp_hidden: Optional[int] = None     # absolute width (else ratio*d)
    lora_rank: int = 0                   # LoRA adapters on the Denses
    lora_alpha: Optional[float] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        if self.window is not None and not self.causal:
            # Every masked impl raises this from inside its scan; the
            # dot baseline would silently drop the window instead —
            # make the contract uniform and early.
            raise ValueError(
                "window (sliding-window attention) requires "
                "causal=True; bidirectional windowed attention is not "
                "implemented")
        # Decode ticks (S=1) attend against the KV cache inside the
        # attention module; the attn_fn (flash/ring/...) is used by the
        # ONE-PASS PREFILL (S>1 from an empty cache), which is plain
        # causal attention over the prompt block — flash-able.
        attn_fn = make_attn_fn(self.attn_impl, causal=self.causal,
                               window=self.window,
                               flash_block_q=self.flash_block_q,
                               flash_block_k=self.flash_block_k)
        mask = None
        if attn_fn is None and not self.decode and self.causal:
            # dot baseline materializes the banded causal mask
            # (bidirectional attention = no mask at all)
            S = x.shape[-2]
            pos = jnp.arange(S)
            mask = banded_causal_mask(pos, pos, self.window)[None, None]
        h = _make_norm(self.norm, self.dtype, self.ln_eps,
                       "ln_attn")(x)
        h = ParallelSelfAttention(
            num_heads=self.num_heads, head_dim=self.head_dim,
            num_kv_heads=self.num_kv_heads, pos_emb=self.pos_emb,
            rope_theta=self.rope_theta, window=self.window,
            dtype=self.dtype, attn_fn=attn_fn, decode=self.decode,
            chunked_prefill=self.chunked_prefill,
            decode_prefix_block=self.decode_prefix_block,
            decode_prefix_impl=self.decode_prefix_impl,
            weight_quant=self.weight_quant,
            kv_quant=self.kv_quant,
            use_bias=self.attn_bias, out_bias=self.attn_out_bias,
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            name="attn")(h, mask)
        x = x + h
        h = _make_norm(self.norm, self.dtype, self.ln_eps,
                       "ln_mlp")(x)
        if self.moe:
            h = MoELayer(num_experts=self.num_experts,
                         hidden=self.mlp_ratio * d, k=self.moe_k,
                         capacity_factor=self.moe_capacity_factor,
                         dtype=self.dtype, name="moe")(h)
        else:
            hidden = self.mlp_hidden or self.mlp_ratio * d
            if self.mlp_impl in ("swiglu", "geglu"):
                # Same gated two-projection block; geglu (Gemma) gates
                # with tanh-gelu instead of silu.
                h = ParallelSwiGLU(hidden=hidden, out=d,
                                   activation=("gelu_tanh"
                                               if self.mlp_impl
                                               == "geglu" else "silu"),
                                   weight_quant=self.weight_quant,
                                   lora_rank=self.lora_rank,
                                   lora_alpha=self.lora_alpha,
                                   dtype=self.dtype, name="mlp")(h)
            elif self.mlp_impl == "gelu":
                h = ParallelMLP(hidden=hidden, out=d,
                                weight_quant=self.weight_quant,
                                lora_rank=self.lora_rank,
                                lora_alpha=self.lora_alpha,
                                dtype=self.dtype, name="mlp")(h)
            else:
                raise ValueError(
                    f"mlp_impl must be gelu|swiglu|geglu, got "
                    f"{self.mlp_impl!r}")
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM. Input [B, S] int tokens → [B, S, V] logits.

    Embedding table and LM head are vocab-sharded over ``model``
    (Megatron layout); activations are pinned (data, seq) so the batch
    and sequence axes stay distributed through every block.
    """

    vocab_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None   # GQA: fewer K/V heads
    pos_emb: str = "learned"             # "learned" | "rope"
    rope_theta: float = 10000.0
    window: Optional[int] = None         # sliding-window attention
    mlp_ratio: int = 4
    max_len: int = 2048
    dtype: Optional[Dtype] = jnp.bfloat16
    attn_impl: str = "blockwise"
    moe_every: int = 0          # 0 = dense; n = every n-th block is MoE
    num_experts: int = 8
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    remat: bool = False
    decode: bool = False        # autoregressive inference w/ KV cache
    # S>1 decode calls append to a non-empty cache (general cache-wide
    # mask) instead of the one-pass empty-cache prefill; see
    # ParallelSelfAttention.chunked_prefill.
    chunked_prefill: bool = False
    # Linear-cache decode attention touches only the filled prefix, in
    # slices this big (ParallelSelfAttention.decode_prefix_block);
    # 0/None = cache-wide-mask path.
    decode_prefix_block: Optional[int] = 256
    decode_prefix_impl: str = "lax"   # "lax" | "pallas" (flash-decode)
    # "int8": block matmul kernels stored int8 + per-channel scales
    # (weight-only, inference; `ops.quantization.quantize_lm_params`).
    # Embedding/head and LayerNorms stay full precision.
    weight_quant: Optional[str] = None
    # "int8": decode KV cache stored int8 with per-(position, head)
    # scales — 2x context length per byte of cache HBM.
    kv_quant: Optional[str] = None
    flash_block_q: int = 128   # Pallas flash tile sizes (bench-sweepable)
    flash_block_k: int = 128
    attn_bias: bool = False    # attention projection biases (GPT-2)
    attn_out_bias: Optional[bool] = None  # Qwen2: qkv bias, no out bias
    ln_eps: float = 1e-6       # LayerNorm epsilon (GPT-2: 1e-5)
    norm: str = "layernorm"    # "layernorm" | "rmsnorm" (LLaMA)
    mlp_impl: str = "gelu"     # "gelu" | "swiglu" (LLaMA)
    mlp_hidden: Optional[int] = None   # absolute MLP width override
    # False: a separate vocab-sharded lm_head param instead of reusing
    # the embedding (LLaMA-family default).
    tied_head: bool = True
    # Input embeddings multiplied by this after lookup (Gemma:
    # sqrt(hidden_size)); the tied LM head reads the UNSCALED table,
    # matching that family's convention. None = 1.
    embed_scale: Optional[float] = None
    # LoRA (Hu et al. 2021): rank-r adapters on every block Dense;
    # train with `models.lora.lora_label_fn` masking the base frozen,
    # merge for serving with `models.lora.merge_lora`.
    lora_rank: int = 0
    lora_alpha: Optional[float] = None

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 return_hidden: bool = False) -> Any:
        if self.pos_emb not in ("learned", "rope"):
            raise ValueError(
                f"pos_emb must be 'learned' or 'rope', "
                f"got {self.pos_emb!r}")
        B, S = tokens.shape
        d = self.num_heads * self.head_dim
        embed = self.param(
            "embed",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 (AXIS_MODEL, None)),
            (self.vocab_size, d), jnp.float32)
        x = jnp.take(embed, tokens, axis=0)
        if self.embed_scale is not None:
            x = x * jnp.asarray(self.embed_scale, x.dtype)
        if self.pos_emb != "rope":
            # Rotary positions live inside the attention (applied to
            # q/k at absolute positions — no learned table, no
            # position state outside the per-block KV cache index);
            # learned positions add a table slice here.
            pos = self.param("pos", nn.initializers.normal(0.02),
                             (self.max_len, d), jnp.float32)
            if self.decode:
                # Position comes from the running cache index, not the
                # input offset (tokens arrive one tick at a time).
                idx = self.variable("cache", "pos_index",
                                    lambda: jnp.zeros((), jnp.int32))
                p = lax.dynamic_slice_in_dim(pos, idx.value, S, axis=0)
                if not self.is_initializing():
                    idx.value = idx.value + S
            else:
                p = pos[:S]
            x = x + p
        x = x.astype(self.dtype)
        x = constrain(x, AXIS_DATA, AXIS_SEQ, None)

        block_cls = TransformerBlock
        if self.remat:
            block_cls = nn.remat(TransformerBlock)
        for i in range(self.num_layers):
            moe = self.moe_every > 0 and (i + 1) % self.moe_every == 0
            x = block_cls(
                num_heads=self.num_heads, head_dim=self.head_dim,
                num_kv_heads=self.num_kv_heads,
                pos_emb=("rope" if self.pos_emb == "rope" else "none"),
                rope_theta=self.rope_theta, window=self.window,
                mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                attn_impl=self.attn_impl, moe=moe,
                num_experts=self.num_experts, moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                decode=self.decode,
                chunked_prefill=self.chunked_prefill,
                decode_prefix_block=self.decode_prefix_block,
                decode_prefix_impl=self.decode_prefix_impl,
                weight_quant=self.weight_quant,
                kv_quant=self.kv_quant,
                flash_block_q=self.flash_block_q,
                flash_block_k=self.flash_block_k,
                attn_bias=self.attn_bias,
                attn_out_bias=self.attn_out_bias,
                ln_eps=self.ln_eps,
                norm=self.norm, mlp_impl=self.mlp_impl,
                mlp_hidden=self.mlp_hidden,
                lora_rank=self.lora_rank,
                lora_alpha=self.lora_alpha,
                name=f"block_{i}")(x)
            x = constrain(x, AXIS_DATA, AXIS_SEQ, None)

        x = _make_norm(self.norm, self.dtype, self.ln_eps,
                       "ln_f")(x)
        head = embed
        if not self.tied_head:
            head = self.param(
                "lm_head",
                nn.with_partitioning(nn.initializers.normal(0.02),
                                     (AXIS_MODEL, None)),
                (self.vocab_size, d), jnp.float32)
        if return_hidden:
            # For the chunked fused head+loss (`chunked_lm_loss`): the
            # [B, S, V] logits never materialize. `head` is the embed
            # when tied, the separate lm_head otherwise.
            return x, head
        # LM head (tied = the embedding): logits sharded over
        # ``model`` on vocab; the CE loss reduces over it with
        # GSPMD-inserted collectives.
        logits = jnp.einsum("bsd,vd->bsv", x,
                            head.astype(self.dtype))
        return constrain(logits, AXIS_DATA, AXIS_SEQ, AXIS_MODEL)


class TransformerBlockStack(nn.Module):
    """The per-stage body for pipeline parallelism: `layers_per_stage`
    blocks applied in sequence, no embedding/head (those live outside the
    pipeline loop). Used via `pipeline_apply_gspmd` with this module's
    params stacked [P, ...] over the ``pipe`` axis."""

    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None
    pos_emb: str = "none"        # "none" | "rope"
    rope_theta: float = 10000.0
    window: Optional[int] = None         # sliding-window attention
    layers_per_stage: int = 1
    mlp_ratio: int = 4
    dtype: Optional[Dtype] = jnp.bfloat16
    attn_impl: str = "blockwise"
    attn_bias: bool = False
    attn_out_bias: Optional[bool] = None
    ln_eps: float = 1e-6
    norm: str = "layernorm"
    mlp_impl: str = "gelu"
    mlp_hidden: Optional[int] = None
    lora_rank: int = 0
    lora_alpha: Optional[float] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                num_heads=self.num_heads, head_dim=self.head_dim,
                num_kv_heads=self.num_kv_heads,
                pos_emb=self.pos_emb, rope_theta=self.rope_theta,
                window=self.window,
                mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                attn_impl=self.attn_impl,
                attn_bias=self.attn_bias,
                attn_out_bias=self.attn_out_bias,
                ln_eps=self.ln_eps,
                norm=self.norm, mlp_impl=self.mlp_impl,
                mlp_hidden=self.mlp_hidden,
                lora_rank=self.lora_rank,
                lora_alpha=self.lora_alpha,
                name=f"block_{i}")(x)
        return x


# ---------------------------------------------------------------------------
# Train step (GSPMD: jit over the mesh; DP/TP/SP/EP collectives inserted
# by the partitioner from the param/activation shardings).
# ---------------------------------------------------------------------------

def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy, [B, S, V] logits vs [B, S] tokens."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), tokens[:, 1:]).mean()


def chunked_lm_loss(hidden: jax.Array, embed: jax.Array,
                    tokens: jax.Array, *, chunk: int = 512) -> jax.Array:
    """Next-token cross entropy fused with the LM head, scanned over
    sequence chunks so the [B, S, V] logits tensor never materializes.

    The plain path's logits are the LM's single biggest activation —
    1 GiB at B8·S2048·V32k bf16, and the dominant allocation in the
    OOM report that sank the blockwise config on a 16 GB chip. Here
    each scan tick computes [B, chunk, V] logits, folds them into the
    running CE sum, and `jax.checkpoint` recomputes them in the
    backward, so peak memory drops by S/chunk at the cost of one extra
    head matmul in the backward (a few % of total step FLOPs).

    Composes with dp (use via `make_lm_train_step(loss_chunk=...)`);
    with sequence parallelism keep the plain loss — the chunk reshape
    would fight the ``seq`` sharding of `hidden`. The batch must
    divide the ``data`` axis (the standard SPMD input contract — a
    ragged batch can trip an XLA partitioner CHECK inside the scan).
    """
    B, S, _ = hidden.shape
    P = S - 1
    total = chunked_weighted_ce(
        hidden[:, :-1], embed, tokens[:, 1:],
        jnp.ones((B, P), jnp.float32), chunk=chunk)
    return total / (B * P)


def chunked_weighted_ce(hidden: jax.Array, head: jax.Array,
                        targets: jax.Array, weights: jax.Array, *,
                        chunk: int) -> jax.Array:
    """SUM of `weights * CE(hidden @ head.T, targets)` computed in
    sequence chunks under `jax.checkpoint` — the shared fused-head CE
    core of `chunked_lm_loss` (causal shift + uniform weights) and
    `bert.chunked_mlm_loss` (masked-position weights): the [B, S, V]
    logits never materialize, each chunk's are recomputed in the
    backward. Padding rows carry weight 0, so ragged S is exact."""
    B, S, D = hidden.shape
    nc = -(-S // chunk)
    pad = nc * chunk - S
    h = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
    y = jnp.pad(targets, ((0, 0), (0, pad)))
    wts = jnp.pad(weights, ((0, 0), (0, pad)))
    h = h.reshape(B, nc, chunk, D).transpose(1, 0, 2, 3)
    y = y.reshape(B, nc, chunk).transpose(1, 0, 2)
    wts = wts.reshape(B, nc, chunk).transpose(1, 0, 2)
    w = head.astype(hidden.dtype)

    @jax.checkpoint
    def tick(total, xs):
        hc, yc, mc = xs
        logits = jnp.einsum("bcd,vd->bcv", hc, w).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, yc)
        return total + (ce * mc).sum(), None

    total, _ = lax.scan(tick, jnp.float32(0.0), (h, y, wts))
    return total


def make_lm_train_step(model: TransformerLM,
                       tx: optax.GradientTransformation, mesh,
                       *, moe_aux_weight: float = 0.01,
                       donate: bool = True,
                       loss_chunk: Optional[int] = None,
                       param_pspecs: Any = None) -> Callable:
    """step(params, opt_state, tokens) -> (params, opt_state, loss).

    `params` = unboxed pytree placed by `init_lm_state` (TP/EP leaves
    sharded per their `nn.Partitioned` annotations, the rest replicated);
    `tokens` [B, S] sharded (data, seq). One jit over the whole mesh: the
    gradient all-reduce over ``data`` (the reference's entire hot path,
    SURVEY §3.2) is inserted by GSPMD because params carry no ``data``
    axis, and XLA's collective combiner provides the tensor-fusion
    batching the reference implements by hand (`docs/tensor-fusion.md`).

    ``param_pspecs``: optional PartitionSpec pytree (e.g. from
    `lm_fsdp_specs`) pinning the UPDATED params — with FSDP this keeps
    the new params born ``data``-sharded so donation reuses the sharded
    buffers and GSPMD lowers the gradient sync as reduce-scatter, not
    all-reduce-then-slice.
    """
    has_moe = model.moe_every > 0

    def data_loss(params, tokens, mutable):
        return _lm_data_loss(model, params, tokens, loss_chunk,
                             mutable)

    def loss_fn(params, tokens):
        if has_moe:
            loss, col = data_loss(params, tokens, ["losses"])
            aux = sum(jnp.asarray(v).sum()
                      for v in jax.tree.leaves(col.get("losses", {})))
            return loss + moe_aux_weight * aux
        loss, _ = data_loss(params, tokens, False)
        return loss

    if param_pspecs is not None:
        from horovod_tpu.parallel.fsdp import constrain_tree

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        if param_pspecs is not None:
            grads = constrain_tree(grads, param_pspecs)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if param_pspecs is not None:
            new_params = constrain_tree(new_params, param_pspecs)
        return new_params, new_opt, loss

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def wrapped(params, opt_state, tokens):
        with use(mesh):
            return jitted(params, opt_state, tokens)

    from horovod_tpu.utils.timeline import step_bracket
    return step_bracket(wrapped)


def _lm_data_loss(model, params, tokens, loss_chunk, mutable):
    """Chunked-vs-plain loss dispatch shared by the train and eval
    steps (one site, so the eval==train-loss invariant can't drift)."""
    if loss_chunk:
        out = model.apply({"params": params}, tokens,
                          return_hidden=True, mutable=mutable)
        (hidden, embed), col = out if mutable else (out, {})
        return chunked_lm_loss(hidden, embed, tokens,
                               chunk=loss_chunk), col
    out = model.apply({"params": params}, tokens, mutable=mutable)
    logits, col = out if mutable else (out, {})
    return lm_loss(logits, tokens), col


def make_lm_eval_step(model: TransformerLM, mesh, *,
                      loss_chunk: Optional[int] = None) -> Callable:
    """eval(params, tokens) -> mean next-token cross entropy (nats).

    The forward-only twin of `make_lm_train_step` — same sharding, no
    gradient/optimizer; perplexity = exp(loss). Use `loss_chunk` to
    keep the [B, S, V] logits from materializing on long sequences
    (same trade as the train step's option).

    For MoE models this is PURE cross entropy: the train step's
    load-balancing aux term (`moe_aux_weight · aux`) is a training
    regularizer, not part of the modeled likelihood, so it is excluded
    here — the right number for perplexity, but expect the train
    step's reported loss to sit `moe_aux_weight · aux` above eval on
    the same batch.
    """
    def ev(params, tokens):
        return _lm_data_loss(model, params, tokens, loss_chunk,
                             False)[0]

    jitted = jax.jit(ev)

    def wrapped(params, tokens):
        with use(mesh):
            return jitted(params, tokens)

    return wrapped


def init_lm_state(model: TransformerLM, tx: optax.GradientTransformation,
                  rng, mesh, sample_tokens, *,
                  sharded_init: bool = False,
                  param_pspecs: Any = None) -> Tuple[Any, Any]:
    """Initialize and mesh-place (params, opt_state).

    Default path: params are initialized on the default device
    (`model.init`), unboxed, and placed per their partition annotations
    (`shard_params`); optimizer slots are pinned to their param's
    placement (`init_opt_state_sharded` — a bare `jit(tx.init)` would
    materialize them replicated).

    ``sharded_init=True``: sharded-at-birth — the init computation
    itself is jitted with `out_shardings` from the partition
    annotations, so every device materializes only its own shard and
    no single device ever holds the full parameter tree. Required once
    the model outgrows one device's HBM (TP/EP models at scale); same
    values as the default path (same keys, same program, partitioned
    by GSPMD).

    ``param_pspecs``: explicit PartitionSpec pytree overriding the
    annotation-derived specs — THE handle for FSDP/ZeRO. Compute it
    once with `lm_fsdp_specs(...)` and pass the same tree here and to
    `make_lm_train_step(param_pspecs=)`; one source of truth means the
    born sharding and the per-step pinning can't drift apart. Implies
    sharded-at-birth.
    """
    from horovod_tpu.parallel.fsdp import init_opt_state_sharded
    if not sharded_init and param_pspecs is None:
        variables = model.init(rng, sample_tokens)
        with use(mesh):
            params = shard_params(mesh, variables["params"])
            opt_state = init_opt_state_sharded(tx, params)
        return params, opt_state

    from jax.sharding import NamedSharding
    toks = jnp.asarray(sample_tokens)
    if param_pspecs is not None:
        specs = param_pspecs
    else:
        shapes = jax.eval_shape(model.init, rng, toks)
        specs = param_specs(shapes["params"])
    out_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P))

    def init_fn(r):
        return unbox(model.init(r, toks)["params"])

    with use(mesh):
        # hvd: disable=HVD003(one-shot sharded param init at setup; out_shardings depends on the call's mesh)
        params = jax.jit(init_fn,
                         out_shardings=out_shardings)(rng)
        opt_state = init_opt_state_sharded(tx, params)
    return params, opt_state


def lm_fsdp_specs(model: TransformerLM, rng, sample_tokens, mesh, *,
                  fsdp_min_elems: Optional[int] = None):
    """The FSDP-overlaid PartitionSpec pytree for the model's params.

    The single source of truth for a ZeRO run — pass the SAME tree to
    `init_lm_state(param_pspecs=...)` and
    `make_lm_train_step(param_pspecs=...)`."""
    from horovod_tpu.parallel.fsdp import (
        DEFAULT_MIN_ELEMS, fsdp_param_specs)
    shapes = jax.eval_shape(model.init, rng,
                            jnp.asarray(sample_tokens))
    return fsdp_param_specs(
        param_specs(shapes["params"]), unbox(shapes["params"]), mesh,
        min_elems=(DEFAULT_MIN_ELEMS if fsdp_min_elems is None
                   else fsdp_min_elems))


def generate(model: TransformerLM, params, prompt, steps: int, *,
             mesh=None, temperature: float = 0.0, rng=None,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             eos_id: Optional[int] = None,
             pad_id: int = 0,
             early_stop: bool = False) -> jax.Array:
    """Autoregressive generation with a KV cache.

    The reference's inference story is a docs recipe for stripping
    Horovod ops out of a frozen graph (`docs/inference.md` there); this
    is the TPU-native inference path in full: a decode-mode clone of the
    trained model (`decode=True` — K/V cached per block, one
    `dynamic_update_slice` per tick), driven by one `lax.scan` over
    prompt + generated positions inside a single jit, TP-composable
    (pass ``mesh``; the cache keeps heads on ``model``).

    `prompt` [B, P] int tokens; returns [B, P + steps]. Greedy at
    ``temperature=0``; otherwise softmax sampling with ``rng``,
    optionally truncated to the ``top_k`` highest-probability tokens
    and/or the ``top_p`` nucleus (smallest set with cumulative
    probability >= top_p).

    ``eos_id``: per-sequence stop token — once a sequence emits it,
    every later position is ``pad_id`` (the output stays a fixed
    [B, P + steps] rectangle; finished sequences simply stop changing,
    the standard batched-serving contract). By default the cache still
    advances for finished rows (same compiled program either way), so
    eos alone is a semantic knob, not a compute saver.

    ``early_stop`` (requires ``eos_id``): make it a compute saver —
    the decode loop runs as a `lax.while_loop` that exits as soon as
    EVERY row has emitted eos, instead of a fixed-length scan. The
    output keeps the same [B, P + steps] rectangle and the same
    post-eos padding contract (unvisited positions are ``pad_id``), so
    tokens are identical to the scan path; only the wall clock
    shrinks. The win compounds under `generate_bucketed`, where each
    bucket stops at its own last finisher.
    The prompt is prefilled in ONE forward pass (the decode-mode
    attention masks S>1 blocks causally against the cached prefix), so
    only the generated tokens pay the per-tick latency.
    """
    prompt = jnp.asarray(prompt)
    B, P = prompt.shape
    if steps <= 0:
        return prompt
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if (top_k is not None or top_p is not None) and temperature <= 0:
        raise ValueError("top_k/top_p require temperature > 0")
    if top_p is not None and not 0 < top_p <= 1:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and not 1 <= top_k <= model.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={model.vocab_size}], "
            f"got {top_k}")
    if eos_id is not None and not 0 <= eos_id < model.vocab_size:
        raise ValueError(
            f"eos_id must be in [0, vocab_size={model.vocab_size}), "
            f"got {eos_id}")
    if eos_id is not None and not 0 <= pad_id < model.vocab_size:
        # Pad tokens are fed back as inputs for finished rows; an
        # out-of-vocab id would gather-clamp silently.
        raise ValueError(
            f"pad_id must be in [0, vocab_size={model.vocab_size}), "
            f"got {pad_id}")
    if early_stop and eos_id is None:
        raise ValueError("early_stop requires eos_id (without a stop "
                         "token there is nothing to stop early on)")
    unbounded = model.pos_emb == "rope" and model.window is not None
    if not unbounded and P + steps - 1 > model.max_len:
        # dynamic_update_slice would clamp writes past the cache end —
        # plausible-looking garbage, so refuse loudly instead. With
        # RoPE + a sliding window the cache is a rolling buffer and
        # positions are unbounded, so any length generates.
        raise ValueError(
            f"prompt ({P}) + steps ({steps}) - 1 exceeds "
            f"max_len={model.max_len} (use pos_emb='rope' with "
            f"window= for unbounded generation)")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    dec_model = model.clone(decode=True)
    # The cache is deterministically zeros; eval_shape gives its
    # structure without running a full-length forward or materializing
    # a second copy of the params.
    shapes = jax.eval_shape(
        dec_model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((B, model.max_len), prompt.dtype))
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])

    args = (dec_model, params, cache, prompt, rng, steps,
            float(temperature), top_k,
            None if top_p is None else float(top_p),
            None if eos_id is None else jnp.asarray(eos_id,
                                                    prompt.dtype),
            jnp.asarray(pad_id, prompt.dtype))
    if mesh is not None:
        with use(mesh):
            gen = _generate_scan(*args, greedy=temperature <= 0,
                                 early_stop=early_stop)
    else:
        gen = _generate_scan(*args, greedy=temperature <= 0,
                             early_stop=early_stop)
    return jnp.concatenate([prompt, gen], axis=1)


@functools.partial(jax.jit,
                   static_argnames=("dec_model", "steps", "greedy",
                                    "top_k", "early_stop"))
def _generate_scan(dec_model, params, cache, prompt, rng, steps,
                   temperature, top_k=None, top_p=None, eos=None,
                   pad=None, *, greedy=False, early_stop=False):
    """The compiled prefill+decode loop — module-level so the jit cache
    persists across `generate` calls (flax Modules hash by their
    dataclass fields, so same model config ⇒ cache hit).

    ``temperature``, ``top_p``, ``eos``, and ``pad`` are traced
    operands, so changing their values reuses the compiled program;
    what recompiles is the static ``greedy`` flag (temperature <= 0 —
    selects the argmax branch), ``top_k`` (a shape operand of
    `lax.top_k`), and toggling ``top_p`` or ``eos`` between None and
    a value (the arg pytree changes)."""

    def last_logits(cache, toks):
        """Apply one decode call and project ONLY the last position
        through the LM head — prefill never materializes the
        [B, P, vocab] logits tensor (the LM's biggest activation, the
        same one chunked_lm_loss exists to avoid)."""
        (hidden, embed), mut = dec_model.apply(
            {"params": params, "cache": cache}, toks,
            return_hidden=True, mutable=["cache"])
        logits = jnp.einsum("bd,vd->bv", hidden[:, -1],
                            embed.astype(hidden.dtype))
        return logits.astype(jnp.float32), mut["cache"]

    def pick(logits, r):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits = logits / temperature
        neg = jnp.finfo(logits.dtype).min
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, neg, logits)
        if top_p is not None:
            logits = nucleus_mask(logits, top_p)
        nxt = jax.random.categorical(r, logits)
        return nxt.astype(prompt.dtype)

    # Prefill: the whole prompt in one forward (fills every block's
    # cache, yields the first generated token).
    rng, r0 = jax.random.split(rng)
    logits, cache = last_logits(cache, prompt)
    tok0 = pick(logits, r0)
    # Per-sequence stop: the eos token itself is emitted, every later
    # position is pad (fixed-rectangle output; the cache still ticks
    # for finished rows — one compiled program either way).
    done0 = (tok0 == eos if eos is not None
             else jnp.zeros(tok0.shape, bool))

    def tick(carry, _):
        cache, tok, r, done = carry
        r, r_tick = jax.random.split(r)
        logits, cache = last_logits(cache, tok[:, None])
        nxt = pick(logits, r_tick)
        if eos is not None:
            nxt = jnp.where(done, pad, nxt)
            done = done | (nxt == eos)
        return (cache, nxt, r, done), nxt

    if early_stop:
        # while_loop twin of the scan below: same tick body writing
        # into a pad-prefilled [B, steps-1] buffer, but the loop exits
        # as soon as every row is done — unvisited columns stay pad,
        # so the output rectangle is identical to the scan path's.
        B = prompt.shape[0]
        buf0 = jnp.full((B, steps - 1), pad, prompt.dtype)

        def cond(state):
            t, carry, _ = state
            done = carry[3]
            return (t < steps - 1) & ~done.all()

        def body(state):
            t, carry, buf = state
            carry, nxt = tick(carry, None)
            buf = lax.dynamic_update_slice(
                buf, nxt[:, None], (jnp.zeros((), t.dtype), t))
            return t + 1, carry, buf

        _, _, outs = lax.while_loop(
            cond, body, (jnp.zeros((), jnp.int32),
                         (cache, tok0, rng, done0), buf0))
        return jnp.concatenate([tok0[:, None], outs], axis=1)

    (_, _, _, _), outs = lax.scan(
        tick, (cache, tok0, rng, done0), None, length=steps - 1)
    return jnp.concatenate([tok0[:, None], outs.T], axis=1)  # [B, steps]


def generate_bucketed(model: TransformerLM, params, prompts,
                      steps: int, **kw):
    """Mixed-length batched serving via length bucketing.

    `generate` shares one prompt length P per call (the KV cache keeps
    a single scalar fill index — docs/inference.md's batched-serving
    contract). This helper makes the documented workaround an API:
    ``prompts`` is a LIST of 1-D int token arrays; same-length prompts
    are grouped into one shared-P `generate` call each, and results
    come back in input order as a list of 1-D [P_i + steps] arrays.
    All `generate` kwargs pass through — eos_id/pad_id keep the same
    post-eos padding contract per row, and ``early_stop=True`` (with
    eos_id) stops each bucket's decode loop at that bucket's last
    finisher instead of always paying all ``steps`` ticks. One
    compile per distinct (length, batch-size) pair — the standard
    serving-bucket trade.
    """
    arrs = [jnp.asarray(p) for p in prompts]
    by_len: dict = {}
    for idx, p in enumerate(arrs):
        if p.ndim != 1:
            raise ValueError(
                f"generate_bucketed wants 1-D prompts, got shape "
                f"{p.shape}; for an already-rectangular batch call "
                f"generate directly")
        by_len.setdefault(p.shape[0], []).append(idx)
    out: list = [None] * len(arrs)
    for n, idxs in by_len.items():
        bkw = kw
        if kw.get("rng") is not None:
            # Independent sample streams per bucket: the same key fed
            # to every call would replay identical Gumbel noise.
            bkw = dict(kw, rng=jax.random.fold_in(kw["rng"], n))
        res = generate(model, params,
                       jnp.stack([arrs[i] for i in idxs]), steps,
                       **bkw)
        for row, i in enumerate(idxs):
            out[i] = res[row]
    return out


# ---------------------------------------------------------------------------
# Slot-aware decode (the device surface of `horovod_tpu.serving`).
#
# `generate` shares ONE scalar `cache_index` across the batch, so every
# row must be at the same fill level — fine for offline batches, fatal
# for continuous batching, where each slot of the decode batch holds a
# different request at a different depth. These primitives generalize
# the linear cache to a SLOT POOL: every cache leaf gains a leading
# [num_slots] axis (so the per-layer `cache_index`/`pos_index` scalars
# become per-slot vectors), prefill appends into one slot's rows via
# the `chunked_prefill` cache-wide-mask path (correct at any fill), and
# the decode tick `jax.vmap`s the B=1 decode step over the slot axis —
# per-slot RoPE offsets, per-slot prefix-attention trip counts, and the
# per-row `dynamic_update_slice` cache writes all fall out of the vmap.
# ---------------------------------------------------------------------------

def slot_decode_model(model: TransformerLM) -> TransformerLM:
    """The decode-mode clone every slot primitive shares. ONE clone
    config (decode + chunked_prefill) serves both prefill chunks (S>1
    appends at arbitrary fill) and S=1 ticks, so the flax-module hash —
    and therefore the jit cache — is shared across all of them."""
    return model.clone(decode=True, chunked_prefill=True)


def init_slot_cache(model: TransformerLM, num_slots: int):
    """Zero-filled slot-pool cache: each leaf of the B=1 decode cache
    with a leading [num_slots] axis (K/V [num_slots, 1, max_len, Hkv,
    D]; the scalar fill indices become [num_slots] vectors)."""
    dec_model = slot_decode_model(model)
    shapes = jax.eval_shape(
        dec_model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, model.max_len), jnp.int32))
    return jax.tree.map(
        lambda s: jnp.zeros((num_slots,) + s.shape, s.dtype),
        shapes["cache"])


def _serve_kv_axis(axis: Optional[str]) -> str:
    """The mesh axis serving KV shards ride (``HVD_SERVE_MESH_AXIS``,
    default the tensor-parallel ``model`` axis — KV heads live with
    their query groups' attention shards)."""
    if axis is not None:
        return axis
    from horovod_tpu.runtime.config import config as _cfg
    return _cfg.serve_mesh_axis or AXIS_MODEL


def shard_slot_cache(cache, mesh, axis: Optional[str] = None):
    """Commit a slot-pool cache (`init_slot_cache` layout) onto
    ``mesh``: KV leaves shard along the HEADS axis — dim 3 of
    [num_slots, 1, max_len, Hkv, ...] (K/V values and their int8-KV
    scale twins both carry Hkv there) — over the serving mesh axis;
    the per-slot fill-index vectors replicate (host-replicated int32
    metadata, one host decision drives all shards). GQA-aware via
    `safe_spec`: a heads count the axis size doesn't divide keeps the
    leaf replicated — KV heads partition with their query groups only
    when they can, never unevenly."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten
    from horovod_tpu.parallel.mesh import _place, safe_spec, sharding
    axis = _serve_kv_axis(axis)
    flat, treedef = tree_flatten_with_path(cache)
    out = []
    for path, leaf in flat:
        spec = (P() if "index" in str(path) else
                safe_spec(mesh, P(None, None, None, axis), leaf.shape))
        out.append(_place(leaf, sharding(mesh, *spec)))
    return tree_unflatten(treedef, out)


def shard_paged_pools(pools, mesh, axis: Optional[str] = None):
    """Commit paged block pools (`init_paged_pools` layout) onto
    ``mesh``: every pool leaf is [num_blocks, 1, block_size, Hkv, ...]
    — the heads axis sits at dim 3 exactly as in the linear slot
    cache — so each device holds its head slice of EVERY block, and a
    host-side block id names a mesh-wide block SHARD set. Same
    GQA-aware degrade as `shard_slot_cache`."""
    from horovod_tpu.parallel.mesh import _place, safe_spec, sharding
    axis = _serve_kv_axis(axis)
    return [
        _place(p, sharding(mesh, *safe_spec(
            mesh, P(None, None, None, axis), p.shape)))
        for p in pools]


def gather_block_rows(pools, block_ids):
    """Pull the [len(block_ids), 1, block_size, ...] rows of every
    pool leaf for a block-id list (KV-block export: the per-leaf
    device buffers a prefill pool hands to a decode pool). Plain
    fancy-index gather — stays on device; callers decide whether to
    bounce through the host (`np.asarray`) or `device_put` straight
    into the destination layout."""
    idx = jnp.asarray(block_ids, jnp.int32)
    return [p[idx] for p in pools]


@functools.partial(jax.jit, static_argnames=("dec_model",),
                   donate_argnums=(1,))
def slot_reset(dec_model, cache, slot):
    """Zero one slot's rows across every cache leaf (alloc/retire
    hygiene: fill indices return to 0; stale K/V past the new fill is
    never attended — the causal masks see positions, not bytes — but
    zeroing the whole row keeps the slot's state trivially inspectable
    and stops idle-slot index creep from inflating the shared vmapped
    tick's prefix-attention trip count)."""
    del dec_model  # part of the key so all slot fns share a cache line
    return jax.tree.map(
        lambda l: l.at[slot].set(jnp.zeros(l.shape[1:], l.dtype)),
        cache)


@hot_path
@functools.partial(jax.jit, static_argnames=("dec_model",),
                   donate_argnums=(2,))
def slot_prefill_chunk(dec_model, params, cache, slot, chunk):
    """Append one [C]-token prompt chunk into slot ``slot``'s cache and
    return ``(cache, last-position logits [V])``.

    Runs the `chunked_prefill` path (cache-wide mask — correct for ANY
    current fill), so a prompt of arbitrary length P streams in as its
    binary decomposition of power-of-two chunks (`prefill_chunks`):
    at most log2(max_len) DISTINCT compiled programs ever, instead of
    one compile per prompt length. ``slot`` is a traced operand, so the
    same program serves every slot."""
    sub = jax.tree.map(lambda l: l[slot], cache)
    (hidden, embed), mut = dec_model.apply(
        {"params": params, "cache": sub}, chunk[None, :],
        return_hidden=True, mutable=["cache"])
    logits = jnp.einsum("d,vd->v", hidden[0, -1],
                        embed.astype(hidden.dtype))
    cache = jax.tree.map(lambda l, s: l.at[slot].set(s), cache,
                         mut["cache"])
    return cache, logits.astype(jnp.float32)


def prefill_chunks(length: int, max_chunk: Optional[int] = None) -> list:
    """Binary decomposition of a prompt length into descending
    power-of-two chunk sizes (13 -> [8, 4, 1]) — the compile-bounded
    schedule `slot_prefill_chunk` is fed with.

    ``max_chunk`` caps every chunk at the largest power of two <=
    max_chunk (200 at max_chunk=64 -> [64, 64, 64, 8]) — the
    Sarathi-style knob behind HVD_PREFILL_CHUNK_BUDGET: the scheduler
    interleaves one bounded chunk with decode ticks instead of
    streaming a whole long prompt back-to-back. Chunk sizes stay
    powers of two, so the compiled-program set stays log2-bounded
    regardless of the cap."""
    if length <= 0:
        raise ValueError(f"prompt length must be positive, got {length}")
    out = []
    if max_chunk is not None and max_chunk >= 1:
        cap = 1 << (int(max_chunk).bit_length() - 1)   # pow2 floor
        out = [cap] * (length // cap)
        length -= cap * (length // cap)
    return out + [1 << b for b in range(length.bit_length() - 1, -1, -1)
                  if length >> b & 1]


def nucleus_mask(logits, top_p):
    """Top-p (nucleus) truncation: mask (to -max) every logit outside
    the smallest prefix of the sorted distribution with cumulative
    probability >= top_p; the first token is always kept. THE one
    nucleus rule — `generate`'s pick and the serving tick's
    `sample_token` both call it, so the two paths cannot drift."""
    neg = jnp.finfo(logits.dtype).min
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = csum - probs < top_p
    # Threshold = smallest kept logit; mask everything below.
    thresh = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, neg, logits)


def sample_token(logits, temperature, top_p, key):
    """One sampled (or greedy) token from [V] logits, with TRACED
    temperature/top_p so one compiled program serves every request mix:
    temperature <= 0 selects argmax, top_p >= 1 disables the nucleus
    truncation (`nucleus_mask`, shared with `generate`'s pick). The
    serving tick vmaps this over slots."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sampled = jax.random.categorical(
        key, jnp.where(top_p < 1.0, nucleus_mask(scaled, top_p),
                       scaled))
    return jnp.where(temperature <= 0.0, greedy, sampled)


def _freeze_cache_indices(new_cache, old_cache, advance):
    """Select per-leaf between the advanced and the input fill indices
    (scalar ``advance`` under the tick's vmap): a lane whose index must
    not move (FREE or mid-prefill slots riding the shared vmapped tick,
    finished-but-unretired slots) keeps its old index. The K/V bytes
    the masked lane wrote at that frozen position are harmless — the
    causal masks attend positions < index, and the next real writer
    (prefill chunk or live tick) lands on the same position — so only
    the cheap scalar index leaves need the select, never the [max_len]
    cache rows."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten
    flat, treedef = tree_flatten_with_path(new_cache)
    old_leaves = jax.tree.leaves(old_cache)
    out = [jnp.where(advance, leaf, old)
           if "index" in str(path) else leaf
           for (path, leaf), old in zip(flat, old_leaves)]
    return tree_unflatten(treedef, out)


@hot_path
@functools.partial(jax.jit, static_argnames=("dec_model",),
                   donate_argnums=(2,))
def slot_decode_tick(dec_model, params, cache, toks, temps, top_ps,
                     rngs, live, done, eos):
    """One continuous-batching decode tick over EVERY slot: vmap of the
    B=1 decode step over the slot axis. Returns ``(cache, next_toks
    [num_slots], new_rngs, done)``. One compiled program serves every
    occupancy pattern; per-slot occupancy state is traced:

    * ``live`` [S] bool — host-known active lanes. Non-live lanes
      (FREE or mid-prefill slots) still ride the vmapped step but
      their cache fill indices are FROZEN (`_freeze_cache_indices`),
      so an idle lane never creeps its index — and with it the shared
      prefix-attention trip count every live slot pays for — and a
      partially prefilled slot's next chunk lands exactly where the
      previous one stopped.
    * ``done`` [S] bool + ``eos`` scalar (pass -1 to disable) — ON-
      DEVICE stop detection: a lane that has emitted eos keeps
      emitting eos (never a post-eos garbage token) and stops
      advancing its cache, all decided on device. The host can
      therefore retire from the (asynchronously transferred) token
      buffer alone, pipeline-depth ticks late, without a second
      device->host sync per tick to check stops.
    """

    def one(sub, tok, temp, top_p, rng, lv, dn):
        (hidden, embed), mut = dec_model.apply(
            {"params": params, "cache": sub}, tok[None, None],
            return_hidden=True, mutable=["cache"])
        new = _freeze_cache_indices(mut["cache"], sub, lv & ~dn)
        logits = jnp.einsum("d,vd->v", hidden[0, -1],
                            embed.astype(hidden.dtype))
        rng, r = jax.random.split(rng)
        nxt = sample_token(logits.astype(jnp.float32), temp, top_p, r)
        nxt = nxt.astype(tok.dtype)
        emit = jnp.where(dn, eos.astype(tok.dtype), nxt)
        return new, emit, rng, dn | (emit == eos)

    return jax.vmap(one)(cache, toks, temps, top_ps, rngs, live, done)


# ---------------------------------------------------------------------------
# Paged slot cache (the device surface of `horovod_tpu.serving.paging`).
#
# The slot-pool cache above still RESERVES a private [max_len] KV region
# per slot, so device KV capacity is num_slots x max_len regardless of
# how long requests actually run — the same per-tensor-allocation waste
# Horovod's fusion buffer removed for gradients, here applied to KV
# state. These primitives carve the cache into fixed-size BLOCKS
# instead (vLLM-style): one shared pool of [num_blocks, 1, block_size,
# ...] rows per cache leaf, and each sequence owns an int32 BLOCK TABLE
# mapping its logical positions to pool blocks. The table and the fill
# index are TRACED operands, so one compiled program serves every
# layout; the per-tick view of a sequence's KV is a gather of its
# blocks (`pool[table]`), reshaped back to the exact [1, max_len, ...]
# linear layout the decode attention already consumes — the compute is
# the SAME flax apply on the SAME values, which is what makes the paged
# path bitwise-equal to the slot pool (pinned by tests). Writes scatter
# only the newly produced rows back into their blocks; lanes that must
# not advance (FREE, mid-prefill, done) route their row to the reserved
# NULL block 0, whose content is never attended (every decode mask
# attends positions < fill only).
# ---------------------------------------------------------------------------

class PagedCacheSpec:
    """Static (hashable — rides jit static args) description of one
    paged slot cache: the B=1 decode-cache tree structure, each leaf's
    kind ("kv" = pooled into blocks, "index" = the per-lane fill
    scalar), the block geometry, and — for the paged-kernel mode —
    each leaf's tree path plus the KV leaves' tail shapes/dtypes (so
    the kernel path can build the per-call staging cache and the
    "paged" collection without a shapes re-eval inside jit). Built
    once per pool via `paged_cache_spec`."""

    __slots__ = ("treedef", "kinds", "block_size", "blocks_per_seq",
                 "paths", "kv_shapes", "kv_dtypes")

    def __init__(self, treedef, kinds, block_size, blocks_per_seq,
                 paths=(), kv_shapes=(), kv_dtypes=()):
        self.treedef = treedef
        self.kinds = tuple(kinds)
        self.block_size = int(block_size)
        self.blocks_per_seq = int(blocks_per_seq)
        self.paths = tuple(tuple(p) for p in paths)
        self.kv_shapes = tuple(tuple(s) for s in kv_shapes)
        self.kv_dtypes = tuple(str(d) for d in kv_dtypes)

    @property
    def view_len(self) -> int:
        return self.block_size * self.blocks_per_seq

    def _key(self):
        return (self.treedef, self.kinds, self.block_size,
                self.blocks_per_seq, self.paths, self.kv_shapes,
                self.kv_dtypes)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return (isinstance(other, PagedCacheSpec)
                and self._key() == other._key())


def paged_cache_spec(model: TransformerLM,
                     block_size: int) -> PagedCacheSpec:
    """Classify the B=1 decode cache's leaves for paging. KV-bearing
    leaves (``cached_key``/``cached_value`` and their int8-KV scale
    twins) carry the max_len axis at position 1 and are pooled into
    blocks; ``cache_index``/``pos_index`` scalars become the per-lane
    fill vector the paged pool keeps outside the tree. Requires
    ``block_size`` to divide ``max_len`` exactly, so the gathered view
    is shape-identical to the linear cache (the bitwise-equality
    contract), and no sliding window (a rolling buffer's slot = pos
    mod window layout has no block-aligned prefix to share)."""
    if model.window is not None:
        raise ValueError(
            "paged KV cache requires window=None (a rolling-window "
            "cache has no block-aligned prefix to page or share)")
    if block_size < 1 or model.max_len % block_size:
        raise ValueError(
            f"block_size must divide max_len={model.max_len} exactly, "
            f"got {block_size}")
    from jax.tree_util import tree_flatten_with_path
    dec_model = slot_decode_model(model)
    shapes = jax.eval_shape(
        dec_model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, model.max_len), jnp.int32))["cache"]
    flat, treedef = tree_flatten_with_path(shapes)
    kinds, paths, kv_shapes, kv_dtypes = [], [], [], []
    for path, leaf in flat:
        paths.append(tuple(getattr(p, "key", str(p)) for p in path))
        if "index" in str(path):
            assert leaf.shape == (), (path, leaf.shape)
            kinds.append("index")
        else:
            assert leaf.shape[:2] == (1, model.max_len), (path,
                                                          leaf.shape)
            kinds.append("kv")
            kv_shapes.append(leaf.shape[2:])
            kv_dtypes.append(leaf.dtype)
    return PagedCacheSpec(treedef, kinds, block_size,
                          model.max_len // block_size,
                          paths=paths, kv_shapes=kv_shapes,
                          kv_dtypes=kv_dtypes)


def init_paged_pools(model: TransformerLM, spec: PagedCacheSpec,
                     num_blocks: int) -> list:
    """Zero-filled block pools: one [num_blocks, 1, block_size, ...]
    array per KV leaf of the B=1 decode cache (flatten order). Block 0
    is the NULL block — never allocated to a sequence; masked lanes
    dump their dead writes there."""
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (block 0 is the reserved null "
            f"block), got {num_blocks}")
    from jax.tree_util import tree_flatten_with_path
    dec_model = slot_decode_model(model)
    shapes = jax.eval_shape(
        dec_model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, model.max_len), jnp.int32))["cache"]
    flat, _ = tree_flatten_with_path(shapes)
    pools = []
    for kind, (path, leaf) in zip(spec.kinds, flat):
        if kind == "kv":
            pools.append(jnp.zeros(
                (num_blocks, 1, spec.block_size) + leaf.shape[2:],
                leaf.dtype))
    return pools


def _paged_view(spec: PagedCacheSpec, pools, table, fill):
    """Assemble one lane's [1, max_len, ...] cache view from its block
    table: KV leaves are `pool[table]` gathers reshaped back to the
    linear layout; index leaves are the lane's fill scalar (every
    layer's cache_index — and pos_index at learned-position models —
    advances in lockstep, so ONE scalar determines them all). The
    table is a traced operand: one compiled program for all layouts."""
    leaves, pi = [], 0
    fill = jnp.asarray(fill, jnp.int32)
    for kind in spec.kinds:
        if kind == "kv":
            g = jnp.take(pools[pi], table, axis=0)   # [nb, 1, bs, ...]
            pi += 1
            g = jnp.moveaxis(g, 1, 0)                # [1, nb, bs, ...]
            leaves.append(g.reshape((1, spec.view_len) + g.shape[3:]))
        else:
            leaves.append(fill)
    from jax.tree_util import tree_unflatten
    return tree_unflatten(spec.treedef, leaves)


# Cache-leaf name -> the "paged" collection name its pool rides under
# (read by `ParallelSelfAttention._paged_decode_attention`).
_POOL_NAMES = {"cached_key": "key_pool", "cached_value": "value_pool",
               "cached_key_scale": "key_scale_pool",
               "cached_value_scale": "value_scale_pool"}


def _paged_staging(spec: PagedCacheSpec, fill, length: int):
    """The paged-KERNEL mode's per-call "cache" collection: a tiny
    [1, length] staging buffer per KV leaf (the apply writes this
    call's new rows at position 0; the tick scatters them into their
    blocks afterwards) plus the index leaves — ``cache_index`` 0 (the
    staging write position) and ``pos_index`` the TRUE fill (learned
    positions slice their table at the absolute position). The real
    KV never materializes here: attention walks the pools through the
    "paged" collection (`_paged_collection`)."""
    from jax.tree_util import tree_unflatten
    leaves, ki = [], 0
    fill = jnp.asarray(fill, jnp.int32)
    for kind, path in zip(spec.kinds, spec.paths):
        if kind == "kv":
            leaves.append(jnp.zeros((1, length) + spec.kv_shapes[ki],
                                    spec.kv_dtypes[ki]))
            ki += 1
        else:
            leaves.append(fill if path[-1] == "pos_index"
                          else jnp.zeros((), jnp.int32))
    return tree_unflatten(spec.treedef, leaves)


def _paged_collection(spec: PagedCacheSpec, pools, table, fill):
    """The read-only "paged" variable collection for one lane's
    apply: each attention module's KV pools land at that module's
    path (key_pool/value_pool, plus the int8-KV scale pools when
    present), alongside the lane's block ``table`` and true ``fill``.
    Under the tick's vmap the pools are closed-over (UNBATCHED — one
    physical pool serves every lane) while table/fill are per-lane."""
    col, pi = {}, 0
    for kind, path in zip(spec.kinds, spec.paths):
        if kind != "kv":
            continue
        parent = col
        for seg in path[:-1]:
            parent = parent.setdefault(seg, {})
        parent[_POOL_NAMES[path[-1]]] = pools[pi]
        parent["table"] = table
        parent["fill"] = fill
        pi += 1
    return col


def _paged_cache_vars(spec: PagedCacheSpec, pools, params, table,
                      fill, length: int, fused: bool):
    """The apply's variable dict for one paged lane: the gathered
    [max_len] view (legacy/oracle path) or the staging + "paged"
    collection pair (kernel path) — THE single dispatch site the
    tick, the prefill chunk, and the speculative verify all share."""
    if fused:
        return {"params": params,
                "cache": _paged_staging(spec, fill, length),
                "paged": _paged_collection(spec, pools, table, fill)}
    return {"params": params,
            "cache": _paged_view(spec, pools, table, fill)}


def _paged_new_rows(spec: PagedCacheSpec, cache, fill, length: int):
    """The rows a decode/prefill apply just wrote into a view cache —
    positions [fill, fill+length) of every KV leaf, [length, ...] each
    (flatten order, matching the pools list)."""
    rows = []
    for kind, leaf in zip(spec.kinds, jax.tree.leaves(cache)):
        if kind == "kv":
            rows.append(lax.dynamic_slice_in_dim(
                leaf, fill, length, axis=1)[0])
    return rows


def _paged_scatter(spec: PagedCacheSpec, pools, rows, bids, offs):
    """Write freshly produced rows into their blocks: ``bids``/``offs``
    are parallel int32 vectors (block id, within-block offset) — one
    batched scatter per leaf. Duplicate (0, off) targets from masked
    lanes land in the null block, where last-writer-wins is harmless
    (null content is never attended)."""
    return [p.at[bids, 0, offs].set(r) for p, r in zip(pools, rows)]


@hot_path
@functools.partial(jax.jit,
                   static_argnames=("dec_model", "spec", "fused"),
                   donate_argnums=(2,))
def paged_prefill_chunk(dec_model, spec: PagedCacheSpec, pools, params,
                        tables, fills, slot, chunk, fused=False):
    """Append one [C]-token prompt chunk into lane ``slot``'s paged
    cache; returns ``(pools, fills, last-position logits [V])``. The
    lane's view is gathered through its block table (``fused=False``,
    the legacy/oracle path) or — the paged-kernel mode — the apply
    writes into a [1, C] staging buffer while attention walks only
    the filled blocks (`_paged_cache_vars`); either way the apply is
    the SAME `chunked_prefill` cache-wide-mask program the linear slot
    pool runs (correct at any fill — including a fill that starts past
    a shared-prefix span the admission matched and skipped), and only
    the chunk's C new rows scatter back into their blocks."""
    table = tables[slot]
    fill = fills[slot]
    C = chunk.shape[0]
    variables = _paged_cache_vars(spec, pools, params, table, fill,
                                  C, fused)
    (hidden, embed), mut = dec_model.apply(
        variables, chunk[None, :],
        return_hidden=True, mutable=["cache"])
    rows = _paged_new_rows(spec, mut["cache"],
                           jnp.int32(0) if fused else fill, C)
    pos = fill + jnp.arange(C, dtype=jnp.int32)
    bids = table[pos // spec.block_size]
    offs = pos % spec.block_size
    pools = _paged_scatter(spec, pools, rows, bids, offs)
    fills = fills.at[slot].set(fill + C)
    logits = jnp.einsum("d,vd->v", hidden[0, -1],
                        embed.astype(hidden.dtype))
    return pools, fills, logits.astype(jnp.float32)


@hot_path
@functools.partial(jax.jit,
                   static_argnames=("dec_model", "spec", "fused"),
                   donate_argnums=(2,))
def paged_decode_tick(dec_model, spec: PagedCacheSpec, pools, params,
                      tables, fills, toks, temps, top_ps, rngs, live,
                      done, eos, fused=False):
    """One continuous-batching decode tick over every lane of a PAGED
    pool: vmap of (cache view -> B=1 decode apply -> sample) over the
    lane axis, then ONE batched scatter of the new KV rows into their
    blocks. ``fused=False`` gathers the lane's whole table into a
    linear view (the legacy/oracle path); ``fused=True`` is the
    paged-kernel mode — attention walks only the FILLED blocks
    (`ops.paged_attention`) and the new row stages at position 0.
    Same occupancy semantics as `slot_decode_tick` — ``live`` gates
    fill advance, ``done`` is the on-device stop — expressed in paged
    form: a non-advancing lane keeps its fill (the freeze) and routes
    its dead row to the null block (the masked write)."""

    def one(table, fill, tok, temp, top_p, rng, lv, dn):
        variables = _paged_cache_vars(spec, pools, params, table,
                                      fill, 1, fused)
        (hidden, embed), mut = dec_model.apply(
            variables, tok[None, None],
            return_hidden=True, mutable=["cache"])
        rows = [r[0] for r in _paged_new_rows(
            spec, mut["cache"], jnp.int32(0) if fused else fill, 1)]
        logits = jnp.einsum("d,vd->v", hidden[0, -1],
                            embed.astype(hidden.dtype))
        rng, r = jax.random.split(rng)
        nxt = sample_token(logits.astype(jnp.float32), temp, top_p, r)
        nxt = nxt.astype(tok.dtype)
        emit = jnp.where(dn, eos.astype(tok.dtype), nxt)
        return rows, emit, rng, dn | (emit == eos), lv & ~dn

    rows, emit, rngs, done, adv = jax.vmap(one)(
        tables, fills, toks, temps, top_ps, rngs, live, done)
    bs = spec.block_size
    # A lane at the P + max_new - 1 == max_len boundary gets one
    # pipelined extra tick with fill == max_len: the table lookup
    # indexes one past the row, take_along_axis's default fill mode
    # yields an out-of-range id, and the scatter below silently DROPS
    # that write (out-of-bounds scatter indices drop) — the surplus
    # token was headed for the discard pile anyway. Keep the fill
    # mode: a clip mode here would instead overwrite the lane's last
    # real block.
    owner = jnp.take_along_axis(tables, (fills // bs)[:, None],
                                axis=1)[:, 0]
    bids = jnp.where(adv, owner, 0)          # masked lanes -> null
    offs = fills % bs
    pools = _paged_scatter(spec, pools, rows, bids, offs)
    fills = jnp.where(adv, fills + 1, fills)
    return pools, emit, rngs, done, fills


# ---------------------------------------------------------------------------
# Speculative decoding in the slot tick (the device surface of
# `models.speculative` generalized to the serving pools).
#
# `generate_speculative` is a batch-1 host loop; serving needs the
# draft-verify round BATCHED over every decode lane with per-lane
# variable acceptance. One jitted ROUND per scheduler step replaces
# the S=1 tick for greedy requests: the draft proposes k tokens per
# lane (a device-chained scan — k+1 ticks, the extra one warming the
# draft cache for full acceptance), the target verifies each lane's
# whole [pending, p_1..p_k] block in ONE chunked append (the same
# S>1-onto-non-empty-cache path prefill chunks ride), acceptance and
# eos truncation are computed ON DEVICE, and both caches rewind by
# setting the per-lane index leaves — rejected rows become invisible
# to the masks and are overwritten by later appends (the linear
# rewind trick; in paged form the stale scattered rows land in
# reserved blocks and are equally invisible). Between 1 and k+1
# tokens retire per round per lane; greedy acceptance makes the
# emitted stream EXACTLY the target's greedy decode, so every pinned
# token-exact contract (vs `generate`, vs the non-spec engine, under
# forced-prefix migration) holds bitwise.
# ---------------------------------------------------------------------------

def _index_leaves(cache):
    """The per-lane index vectors of a slot cache, flatten order —
    captured before a speculative round so the rewind can restore
    pre-round + n_emit exactly."""
    from jax.tree_util import tree_flatten_with_path
    flat, _ = tree_flatten_with_path(cache)
    return [leaf for path, leaf in flat if "index" in str(path)]


def _rewind_indices(cache, pre, delta):
    """Set every per-lane index leaf to ``pre + delta`` (the
    speculative rewind: pre-round fill plus the tokens the round
    actually consumed; 0 delta freezes a masked lane). KV bytes past
    the rewound index are stale but invisible — every decode mask
    attends positions < index only, and the next append overwrites
    them (the same contract `models.speculative._rewind` relies on)."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten
    flat, treedef = tree_flatten_with_path(cache)
    out, pi = [], 0
    for path, leaf in flat:
        if "index" in str(path):
            out.append((pre[pi] + delta).astype(leaf.dtype))
            pi += 1
        else:
            out.append(leaf)
    return tree_unflatten(treedef, out)


def _spec_draft_chain(drf_model, drf_params, drf_cache, toks, adv, k):
    """k+1 vmapped draft ticks, device-chained (no host sync): tick j
    feeds the previous greedy pick, so the chain proposes p_1..p_k
    (the k+1-th pick is discarded — that tick exists to write p_k's
    K/V, which a FULL acceptance needs in the draft cache; partial
    acceptances rewind it away). Masked lanes ride with frozen
    indices. Returns (drf_cache, proposals [L, k+1])."""

    def tick(carry, _):
        dcache, cur = carry

        def one(sub, tok, lv):
            (hidden, embed), mut = drf_model.apply(
                {"params": drf_params, "cache": sub}, tok[None, None],
                return_hidden=True, mutable=["cache"])
            new = _freeze_cache_indices(mut["cache"], sub, lv)
            logits = jnp.einsum("d,vd->v", hidden[0, -1],
                                embed.astype(hidden.dtype))
            return new, jnp.argmax(logits, -1).astype(tok.dtype)

        dcache, nxt = jax.vmap(one)(dcache, cur, adv)
        return (dcache, nxt), nxt

    (drf_cache, _), props = lax.scan(tick, (drf_cache, toks), None,
                                     length=k + 1)
    return drf_cache, jnp.swapaxes(props, 0, 1)        # [L, k+1]


def _spec_accept(props, greedy, pending, adv, done, eos, k: int):
    """The acceptance rule, batched: per lane, the longest prefix of
    ``props`` matching the target's greedy picks, plus the target's
    own next token — truncated at the first emitted eos (on-device
    stop, mirroring the tick's done semantics: a done lane re-emits
    eos once and never advances). Returns (emitted [L, k+1] — first
    n_emit columns are the round's tokens, later columns padding —
    n_emit [L], done, next pending token [L], proposed [L])."""
    match = props == greedy[:, :k]
    a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    jj = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    g_at_a = jnp.take_along_axis(greedy, a[:, None], axis=1)  # [L, 1]
    props_pad = jnp.concatenate([props, props[:, :1]], axis=1)
    emitted = jnp.where(jj < a[:, None], props_pad,
                        jnp.where(jj == a[:, None], g_at_a,
                                  jnp.zeros_like(g_at_a)))
    n = a + 1
    hit = (emitted == eos) & (jj <= a[:, None])
    eos_idx = jnp.min(jnp.where(hit, jj, k + 1), axis=1)
    n = jnp.minimum(n, eos_idx + 1)
    new_done = done | (adv & (eos_idx <= a))
    # Done-but-unretired lanes mirror the tick: one eos re-emit, no
    # advance. Non-live lanes emit nothing.
    n = jnp.where(adv, n, jnp.where(done, 1, 0))
    emitted = jnp.where((~adv & done)[:, None] & (jj == 0),
                        eos.astype(emitted.dtype), emitted)
    last = jnp.take_along_axis(
        emitted, jnp.clip(n - 1, 0, k)[:, None], axis=1)[:, 0]
    toks_out = jnp.where(adv, last,
                         jnp.where(done, eos.astype(pending.dtype),
                                   pending)).astype(pending.dtype)
    proposed = jnp.where(adv, k, 0)
    return emitted, n, new_done, toks_out, proposed


@hot_path
@functools.partial(jax.jit,
                   static_argnames=("dec_model", "drf_model", "k"),
                   donate_argnums=(4, 5))
def slot_spec_round(dec_model, drf_model, params, drf_params, cache,
                    drf_cache, toks, live, done, eos, k):
    """One speculative draft-verify round over every LINEAR slot lane
    (greedy only — the spec-serving contract). Returns ``(cache,
    drf_cache, emitted [L, k+1], n_emit [L], done, toks, proposed)``;
    each live lane retires 1..k+1 tokens, bitwise the target's greedy
    stream."""
    adv = live & ~done
    pre_t = _index_leaves(cache)
    pre_d = _index_leaves(drf_cache)
    drf_cache, props = _spec_draft_chain(drf_model, drf_params,
                                         drf_cache, toks, adv, k)
    block = jnp.concatenate([toks[:, None], props[:, :k]], axis=1)

    def verify(sub, row, lv):
        (hidden, embed), mut = dec_model.apply(
            {"params": params, "cache": sub}, row[None, :],
            return_hidden=True, mutable=["cache"])
        new = _freeze_cache_indices(mut["cache"], sub, lv)
        logits = jnp.einsum("sd,vd->sv", hidden[0],
                            embed.astype(hidden.dtype))
        return new, jnp.argmax(logits, -1).astype(row.dtype)

    cache, greedy = jax.vmap(verify)(cache, block, adv)
    emitted, n_emit, done, toks, proposed = _spec_accept(
        props[:, :k], greedy, toks, adv, done, eos, k)
    delta = jnp.where(adv, n_emit, 0)
    cache = _rewind_indices(cache, pre_t, delta)
    drf_cache = _rewind_indices(drf_cache, pre_d, delta)
    return cache, drf_cache, emitted, n_emit, done, toks, proposed


@hot_path
@functools.partial(jax.jit,
                   static_argnames=("dec_model", "drf_model", "spec",
                                    "k", "fused"),
                   donate_argnums=(5, 6))
def paged_spec_round(dec_model, drf_model, spec: PagedCacheSpec,
                     params, drf_params, pools, drf_cache, tables,
                     fills, toks, live, done, eos, k, fused=False):
    """The paged twin of `slot_spec_round`: the draft rides its own
    linear slot cache (small model — the paging win is the target's),
    the verify is a vmapped S=k+1 paged append (gathered view or the
    block-walking kernel path, per ``fused``), the k+1 new rows per
    lane scatter into their blocks, and the rewind is just the fills
    vector — stale rows beyond it sit in the lane's RESERVED blocks,
    invisible to every mask and overwritten by later appends (block
    reservations already cover prompt + max_new; the engine's
    spec-mode submit bound keeps even the k-token overshoot inside
    max_len, and out-of-table writes drop, per `paged_decode_tick`'s
    boundary contract)."""
    adv = live & ~done
    pre_d = _index_leaves(drf_cache)
    drf_cache, props = _spec_draft_chain(drf_model, drf_params,
                                         drf_cache, toks, adv, k)
    block = jnp.concatenate([toks[:, None], props[:, :k]], axis=1)

    def verify(table, fill, row):
        variables = _paged_cache_vars(spec, pools, params, table,
                                      fill, k + 1, fused)
        (hidden, embed), mut = dec_model.apply(
            variables, row[None, :],
            return_hidden=True, mutable=["cache"])
        rows = _paged_new_rows(spec, mut["cache"],
                               jnp.int32(0) if fused else fill, k + 1)
        logits = jnp.einsum("sd,vd->sv", hidden[0],
                            embed.astype(hidden.dtype))
        return rows, jnp.argmax(logits, -1).astype(row.dtype)

    rows, greedy = jax.vmap(verify)(tables, fills, block)
    emitted, n_emit, done, toks, proposed = _spec_accept(
        props[:, :k], greedy, toks, adv, done, eos, k)
    # The draft cache rewinds like the linear round's: without it the
    # draft index would creep k+1 per round regardless of acceptance
    # (wrong RoPE offsets, attention over rejected-token KV —
    # acceptance decays toward chance and the index eventually
    # overruns draft max_len). Output would STAY bitwise (the verify
    # decides every token) — only the speedup would silently rot.
    drf_cache = _rewind_indices(drf_cache, pre_d,
                                jnp.where(adv, n_emit, 0))
    bs = spec.block_size
    pos = fills[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    # Same boundary semantics as the tick: take_along_axis's fill
    # mode turns past-the-table lookups into out-of-range ids whose
    # scatter writes DROP (only ever overshoot rows), and masked
    # lanes route every row to the null block.
    owner = jnp.take_along_axis(tables, pos // bs, axis=1)
    bids = jnp.where(adv[:, None], owner, 0)
    offs = pos % bs
    pools = _paged_scatter(spec, pools, rows, bids, offs)
    fills = fills + jnp.where(adv, n_emit, 0)
    return (pools, fills, drf_cache, emitted, n_emit, done, toks,
            proposed)


@hot_path
@functools.partial(jax.jit, static_argnames=("dec_model",),
                   donate_argnums=(2,))
def slot_prefill_advance(dec_model, params, cache, slot, chunk):
    """Draft-cache prompt advance: `slot_prefill_chunk` minus the
    LM-head matmul — spec decode only needs the draft's KV warm, its
    logits are never read during prefill (the FIRST token is always
    the target's)."""
    sub = jax.tree.map(lambda l: l[slot], cache)
    _, mut = dec_model.apply({"params": params, "cache": sub},
                             chunk[None, :], return_hidden=True,
                             mutable=["cache"])
    return jax.tree.map(lambda l, s: l.at[slot].set(s), cache,
                        mut["cache"])


@functools.partial(jax.jit, donate_argnums=(0,))
def paged_copy_block(pools, src, dst):
    """Device-side block copy (every KV leaf) — the copy-on-write
    primitive: before a lane appends into a block whose refcount > 1
    (a forked sequence sharing its tail), the allocator gives it a
    private copy and this materializes the bytes."""
    return [p.at[dst].set(p[src]) for p in pools]


def serving_params(params, dtype=jnp.bfloat16):
    """Cast the big (ndim >= 2) float params to the serving dtype.

    Params are STORED f32 (training master weights); the modules cast
    to the compute dtype at every use. Under the decode scan that cast
    sits inside the loop, so unless XLA hoists it the chip re-reads
    the f32 bytes every tick — double the weight HBM traffic decode is
    bound by. Pre-casting pins the win host-side: matrices and the
    embedding land bf16 (each use site's `astype` becomes a no-op — at
    rope archs the tokens are bit-identical, oracle-tested), while 1-D
    params (LayerNorm/RMSNorm scales, biases) stay f32 for their
    higher-precision epilogues. int8-quantized trees
    (`quantize_lm_params`) already store int8 + f32 scales; the scales
    are 1-D so this is a safe no-op on top.
    """
    def cast(p):
        if p.ndim >= 2 and jnp.issubdtype(p.dtype, jnp.floating):
            return p.astype(dtype)
        return p
    return jax.tree.map(cast, params)


def lm_param_specs(model: TransformerLM, rng, sample_tokens):
    """PartitionSpec pytree for the model's params (for inspection/tests)."""
    variables = jax.eval_shape(model.init, rng, sample_tokens)
    return param_specs(variables["params"])
