"""LoRA fine-tuning utilities (Hu et al. 2021).

No reference equivalent — parameter-efficient tuning postdates the
reference. The adapters live INSIDE the TP Dense modules
(`TransformerLM(lora_rank=r)`: every block Dense gains `lora_a`
[in, r] replicated + `lora_b` [r, out] sharded like the kernel, B
zero-init so the adapter starts as an exact no-op), so TP/SP sharding,
the flash kernel, decode, and the DP gradient path all apply
unchanged. These helpers supply the two things the modules don't:

* freezing the base — `lora_label_fn(params)` labels every leaf
  "lora" or "frozen" for `optax.multi_transform` (or build a bool
  mask with `lora_mask`); only A/B receive updates, and with
  multi_transform + `set_to_zero` the frozen base carries no
  optimizer state (the memory point of LoRA);
* serving — `merge_lora(params, alpha=...)` folds `W + (alpha/r)·A@B`
  into each kernel and drops the adapter leaves, yielding a plain
  tree for `generate`, `quantize_lm_params`, or `compat.hf` export.

Distributed semantics fall out of the existing machinery: gradients
for A/B average over ``data`` like any other param (GSPMD psum), and
the row-parallel adapter's contraction reduce rides the same
all-reduce slot as its base kernel's.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

LORA_LEAVES = ("lora_a", "lora_b")


def _is_lora_path(path) -> bool:
    return any(getattr(k, "key", None) in LORA_LEAVES for k in path)


def lora_label_fn(params: Any) -> Any:
    """Pytree of "lora" / "frozen" labels shaped like ``params`` — the
    `optax.multi_transform` param_labels argument:

        tx = optax.multi_transform(
            {"lora": optax.adamw(1e-4), "frozen": optax.set_to_zero()},
            lora_label_fn)
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: "lora" if _is_lora_path(path) else "frozen",
        params)


def lora_mask(params: Any) -> Any:
    """Bool pytree (True = trainable adapter leaf) for `optax.masked`."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: _is_lora_path(path), params)


def graft_base(pretrained: Any, lora_params: Any) -> Any:
    """Overlay a pretrained base tree under freshly-initialized
    adapters: every non-adapter leaf comes from ``pretrained``, the
    `LORA_LEAVES` keep their fresh (zero-B, exact-no-op) init — the
    standard start of a LoRA fine-tune (`examples/jax_lora_finetune`).
    Trees must share structure apart from the adapter leaves."""
    def walk(pre, tree):
        if not isinstance(tree, dict):
            return pre
        out = {}
        for key, val in tree.items():
            if key in LORA_LEAVES:
                out[key] = val
            else:
                out[key] = walk(pre[key], val)
        return out
    return walk(pretrained, lora_params)


def merge_lora(params: Any, *, model: Any = None,
               rank: Optional[int] = None,
               alpha: Optional[float] = None) -> Any:
    """Fold every adapter into its base kernel and drop the A/B leaves.

    Pass ``model`` (the `TransformerLM` the tree belongs to) and the
    scale is read from its ``lora_rank``/``lora_alpha`` fields — the
    safe form, immune to forgetting a non-default alpha. Without it,
    ``rank`` defaults to the A matrices' own trailing dim and
    ``alpha`` to ``rank`` (scale 1); a model trained with a custom
    ``lora_alpha`` MUST have it passed one way or the other or the
    merge silently mis-scales. Returns a plain tree interchangeable
    with a `lora_rank=0` model's (what `model.clone(lora_rank=0)`
    expects), ready for serving, int8 quantization, or HF export.
    """
    if model is not None:
        if rank is None:
            rank = model.lora_rank or None
        if alpha is None:
            alpha = model.lora_alpha
    def walk(node):
        if not isinstance(node, dict):
            return node
        if "lora_a" in node and "lora_b" in node:
            a = jnp.asarray(node["lora_a"], jnp.float32)
            b = jnp.asarray(node["lora_b"], jnp.float32)
            r = rank if rank is not None else a.shape[-1]
            scale = (alpha if alpha is not None else float(r)) / r
            out = {k: walk(v) for k, v in node.items()
                   if k not in LORA_LEAVES}
            if "kernel" not in out:
                raise ValueError(
                    "lora_a/lora_b found without a sibling kernel "
                    "(quantized tree? merge BEFORE quantize_lm_params)")
            out["kernel"] = (jnp.asarray(out["kernel"], jnp.float32)
                             + scale * (a @ b)).astype(
                                 jnp.asarray(node["kernel"]).dtype)
            return out
        return {k: walk(v) for k, v in node.items()}

    return walk(params)
