"""VGG-16 — the bandwidth-bound benchmark case.

The reference reports 79 % scaling efficiency for VGG-16 vs 90 % for
ResNet-101 (`README.md:32`) because VGG's 138 M parameters make its
allreduce bandwidth-bound — the case tensor fusion exists for
(`docs/tensor-fusion.md`). This model backs the fusion-threshold sweep
in BASELINE.md's benchmark configs.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M")


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for v in VGG16_CFG:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
