"""Model zoo backing the reference's examples and benchmarks.

The reference itself ships no model library — its examples and README
benchmarks use MNIST convnets, word2vec, and tf_cnn_benchmarks'
ResNet-101 / Inception V3 / VGG-16 (`README.md:27-32`, SURVEY §6). These
TPU-first implementations (flax.linen, NHWC, bfloat16-friendly) back
`examples/`, `bench.py`, and the scaling-efficiency targets in
BASELINE.md.
"""

from horovod_tpu.models.mnist import MnistConvNet
from horovod_tpu.models.resnet import ResNet, ResNet50, ResNet101, ResNet152
from horovod_tpu.models.vgg import VGG16
from horovod_tpu.models.inception import InceptionV3
from horovod_tpu.models.word2vec import Word2Vec
from horovod_tpu.models.lora import (graft_base, lora_label_fn,
                                     lora_mask, merge_lora)
from horovod_tpu.models.speculative import generate_speculative
from horovod_tpu.models.bert import (BertBase, BertLarge, BertMLM,
                                     chunked_mlm_loss,
                                     make_mlm_batch, make_mlm_train_step,
                                     mlm_loss)
from horovod_tpu.models.vit import VisionTransformer, ViT_B16, ViT_S16
from horovod_tpu.models.train import make_cnn_train_step
from horovod_tpu.models.transformer import (
    TransformerLM, generate, generate_bucketed, init_lm_state,
    lm_fsdp_specs, make_lm_eval_step, make_lm_train_step,
    serving_params,
)

__all__ = [
    "MnistConvNet", "ResNet", "ResNet50", "ResNet101", "ResNet152",
    "VGG16", "InceptionV3", "Word2Vec", "VisionTransformer",
    "ViT_B16", "ViT_S16", "make_cnn_train_step",
    "BertBase", "BertLarge", "BertMLM", "chunked_mlm_loss",
    "make_mlm_batch",
    "make_mlm_train_step", "mlm_loss",
    "graft_base", "lora_label_fn", "lora_mask", "merge_lora",
    "generate_speculative",
    "TransformerLM", "generate", "generate_bucketed", "init_lm_state",
    "lm_fsdp_specs", "make_lm_eval_step", "make_lm_train_step",
    "serving_params",
]
