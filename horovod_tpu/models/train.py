"""SPMD training-step builder for flax CNN models (BatchNorm state).

The CNN analogue of `horovod_tpu.jax.make_train_step` for models with
mutable `batch_stats` and dropout RNG — the training loop shape of the
reference's `examples/tensorflow_mnist.py` / tf_cnn_benchmarks runs,
built the TPU way: one jitted shard_map over the `data` axis with fused
gradient psum (tensor fusion) and donated state.

BatchNorm stats stay per-replica-local and are then allreduce-averaged
like the reference's effective behavior under checkpoint-on-rank-0 (each
GPU keeps local stats; averaging keeps replicas consistent so the
rank-0 checkpoint contract of SURVEY §5.4 holds).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops.fusion import (combiner_override_options,
                                    fused_allreduce_tree)
from horovod_tpu.runtime import state as _state


def make_cnn_train_step(model, tx: optax.GradientTransformation,
                        *, mesh=None, axis_name: Optional[str] = None,
                        fusion_threshold: Optional[int] = None,
                        reduce_dtype: Optional[Any] = None,
                        donate: bool = True,
                        remat: bool = False,
                        examples_per_step: Optional[float] = None,
                        flops_per_step: Optional[float] = None
                        ) -> Callable:
    """Returns step(train_state, batch, rng) -> (train_state, loss) where
    train_state = {params, batch_stats, opt_state} (a plain dict pytree,
    replicated) and batch = (images, labels) sharded on dim 0.

    remat=True wraps the forward pass in jax.checkpoint, trading FLOPs
    for HBM — the standard TPU recipe for deep CNNs at large batch.

    Every returned step is bracketed by the observability plane
    (docs/observability.md): the `hvd_training_steps_total` counter
    and `hvd_training_step_seconds` cadence histogram always record;
    declaring the step's work turns on the throughput gauges —
    ``examples_per_step`` drives `hvd_training_tokens_per_s` and
    ``flops_per_step`` (analytic, e.g. bench.py's per-image tables)
    the `hvd_training_mfu` gauge against the device's known peak
    (`utils/profile_analysis.py` math).
    """
    st = _state.check_initialized()
    mesh = mesh or st.mesh
    axis = axis_name or st.axis_name
    # An hvd.DistributedOptimizer performs its own gradient allreduce
    # (possibly compressed — PowerSGD must see RAW local grads, and a
    # second mean would also waste a bucket pass); the step factory
    # only reduces for plain optax transforms. The factory's own wire
    # knobs would then be silently dead — refuse instead of letting a
    # caller believe their reduce_dtype took effect.
    from horovod_tpu.jax import _DistributedTransformation
    tx_distributed = isinstance(tx, _DistributedTransformation)
    if tx_distributed and (fusion_threshold is not None
                           or reduce_dtype is not None):
        raise ValueError(
            "tx is an hvd.DistributedOptimizer, which owns the "
            "gradient allreduce — pass fusion_threshold/reduce_dtype "
            "to DistributedOptimizer(...) instead of the step factory")

    def loss_fn(params, batch_stats, images, labels, rng):
        def fwd(p, imgs):
            return model.apply(
                {"params": p, "batch_stats": batch_stats},
                imgs, train=True, mutable=["batch_stats"],
                rngs={"dropout": rng})
        if remat:
            fwd = jax.checkpoint(fwd)
        logits, mutated = fwd(params, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, mutated["batch_stats"]

    def step(state, batch, rng):
        images, labels = batch
        rng = jax.random.fold_in(rng, lax.axis_index(axis))
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"], state["batch_stats"],
                                   images, labels, rng)
        if not tx_distributed:
            grads = fused_allreduce_tree(
                grads, axis_name=axis, average=True,
                threshold=fusion_threshold, reduce_dtype=reduce_dtype)
        loss = lax.pmean(loss, axis)
        new_stats = jax.tree.map(lambda x: lax.pmean(x, axis), new_stats)
        updates, new_opt = tx.update(grads, state["opt_state"],
                                     state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return ({"params": new_params, "batch_stats": new_stats,
                 "opt_state": new_opt}, loss)

    sharded = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    donate_argnums = (0,) if donate else ()
    from horovod_tpu.utils.timeline import step_bracket
    jitted = step_bracket(jax.jit(
        sharded, donate_argnums=donate_argnums,
        compiler_options=combiner_override_options() or None))
    return _obs_step(_chaos_step(jitted),
                     tokens_per_step=examples_per_step,
                     flops_per_step=flops_per_step)


def _chaos_step(step_fn):
    """Chaos sites for one train-step invocation (host-side wrapper;
    disarmed cost is one global None check per step):

    * ``step_exception`` — a worker dies mid-step (the reference's
      "one rank raised" scenario): raises `ChaosError` before the
      dispatch, so the step never ran and state was not consumed.
    * ``grad_nan`` — a diverged step: the returned loss AND params are
      poisoned with NaN, exactly what an inf/NaN gradient produces
      after `apply_updates` — the `NaNGuard` rollback path's fault.
    """
    from horovod_tpu.resilience import chaos

    def stepped(state, batch, rng):
        if chaos.fires("step_exception"):
            raise chaos.ChaosError(
                "injected worker exception mid-step "
                "(site step_exception)")
        new_state, loss = step_fn(state, batch, rng)
        if chaos.fires("grad_nan"):
            nan = jnp.float32(jnp.nan)
            new_state = dict(
                new_state,
                params=jax.tree.map(lambda x: x * nan.astype(x.dtype),
                                    new_state["params"]))
            loss = loss * nan
        return new_state, loss

    # `__wrapped__` keeps resolving to the innermost JITTED step (the
    # contract step_bracket established and tests/test_fusion.py's HLO
    # introspection relies on: `step.__wrapped__.lower(...)`).
    stepped.__wrapped__ = getattr(step_fn, "__wrapped__", step_fn)
    return stepped


def _obs_step(step_fn, *, tokens_per_step=None, flops_per_step=None,
              name: str = "train_step"):
    """Observability bracket around one train-step invocation: step
    cadence into `hvd_training_step_seconds`/`hvd_training_steps_total`
    and, when the work per step is declared, the tokens-per-second and
    MFU gauges (obs/profiling.StepProfiler). Failed steps (a chaos
    `step_exception`, a real fault) are NOT recorded — the cadence
    histogram is the healthy-step distribution."""
    import time as _time

    from horovod_tpu.obs import straggler as _straggler
    from horovod_tpu.obs.profiling import StepProfiler
    prof = StepProfiler(name, tokens_per_step=tokens_per_step,
                        flops_per_step=flops_per_step)

    def stepped(state, batch, rng):
        t_enter = _time.time()
        with prof.step():
            out = step_fn(state, batch, rng)
        # The fusion-buffer cycle's straggler leg (obs/straggler.py):
        # each step hosts one bucketed-allreduce cycle, and its
        # host-side enter/exit pair is the per-rank timestamp the
        # cross-rank skew report is built from. Failed steps (the
        # chaos step_exception above raised) are skipped, like the
        # cadence histogram.
        _straggler.tracker().record("fusion_cycle",
                                    _time.time() - t_enter)
        return out

    stepped.__wrapped__ = getattr(step_fn, "__wrapped__", step_fn)
    stepped.__obs_profiler__ = prof
    return stepped


def init_cnn_state(model, tx: optax.GradientTransformation, rng,
                   sample_input) -> dict:
    """Initialize {params, batch_stats, opt_state} for a CNN model.

    init is jitted: eager tracing dispatches every initializer op
    individually, which takes minutes for Inception-sized models."""
    # hvd: disable=HVD003(one-shot model init at setup — jitted for tracing speed, not reused)
    variables = jax.jit(lambda r, x: model.init(r, x, train=False))(
        rng, sample_input)
    # Strip nn.Partitioned boxes (TP-annotated models like ViT): the
    # train step passes plain arrays through apply, same as the LM
    # path; CNN models without annotations are untouched.
    from horovod_tpu.parallel.tensor import unbox
    params = unbox(variables["params"])
    batch_stats = variables.get("batch_stats", {})
    return {"params": params, "batch_stats": batch_stats,
            "opt_state": tx.init(params)}
