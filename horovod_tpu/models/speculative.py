"""Speculative decoding (greedy): draft proposes, target verifies.

No reference equivalent — serving-side decode acceleration postdates
the reference. A small DRAFT model autoregressively proposes ``k``
tokens (k cheap ticks), then the TARGET model scores the whole
``[pending, p_1..p_k]`` block in ONE ``chunked_prefill`` append (the
S>1-onto-a-non-empty-cache path built for exactly this); the longest
prefix of proposals matching the target's argmax is accepted, plus
the target's own next token — between 1 and k+1 tokens per target
forward. Greedy acceptance makes the output EXACTLY the target
model's greedy decode — the draft only changes how many target
forward passes are spent per token (oracle:
`tests/test_speculative.py` pins token equality with
`models.generate`).

The cache trick: verifying writes K/V for all proposed positions; on
a rejection at offset ``a`` the caches must forget the rejected tail.
With the LINEAR cache that is just rewinding the per-layer
``cache_index`` (and ``pos_index``) scalars — entries past the index
are invisible to the attention mask and get overwritten by later
appends. Rolling-window caches physically overwrite slots, so
``window`` models are rejected (use plain `generate`).

Execution model: a HOST loop (acceptance length is data-dependent)
over per-shape jitted apply steps — the draft tick, the k-wide
verify, and single-tick tail each compile once per shape and are
cached across calls (`_jitted_step` keys on the flax module's
dataclass fields). The draft ticks chain device-side (no per-tick
host sync); one readback per ROUND (the proposals, when the verify
comparison needs them on host) is inherent to host-side control
flow.

Scope: batch 1 (the cache index is one scalar per layer — per-row
acceptance divergence cannot share it), greedy only (sampling needs
rejection-resampling; the greedy case has an exact-equality oracle).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _rewind(cache: Any, n: int) -> Any:
    """Every per-layer ``cache_index`` / ``pos_index`` scalar set to
    ``n`` — the rejected tail becomes invisible (mask) and will be
    overwritten by the next append."""
    def fix(path, leaf):
        key = getattr(path[-1], "key", None)
        if key in ("cache_index", "pos_index"):
            return jnp.asarray(n, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def _zeros_cache(model, B, prompt_dtype):
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((B, model.max_len), prompt_dtype))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


@functools.lru_cache(maxsize=None)
def _jitted_step(model, mode: str):
    """Compiled decode-apply for one model config.

    ``mode``: "last" — logits for the final position only (ticks,
    prefill: never materializes [1, S, vocab]); "all" — logits for
    every fed position (the verify block); "advance" — no head math
    at all (the draft's prompt prefill only warms its cache)."""

    def f(params, cache, toks):
        (hidden, head), mut = model.apply(
            {"params": params, "cache": cache}, toks,
            return_hidden=True, mutable=["cache"])
        if mode == "advance":
            return mut["cache"]
        h = hidden[:, -1:] if mode == "last" else hidden
        logits = jnp.einsum("bsd,vd->bsv", h, head.astype(h.dtype))
        return logits.astype(jnp.float32), mut["cache"]

    return jax.jit(f)


def generate_speculative(draft_model, draft_params, target_model,
                         target_params, prompt, steps: int, *,
                         k: int = 4,
                         return_stats: bool = False):
    """Greedy generation from ``target_model`` accelerated by
    ``draft_model`` proposals; returns ``[1, P + steps]`` tokens
    identical to `generate(target_model, ..., temperature=0)`.

    ``k``: proposals per round. Each round costs k draft ticks + ONE
    target forward over k+1 positions and yields between 1 and k+1
    tokens — the target's sequential-tick count drops by the
    acceptance rate, which is the entire speedup.
    """
    prompt = jnp.asarray(prompt)
    if prompt.ndim != 2 or prompt.shape[0] != 1:
        raise ValueError(
            f"speculative decoding is batch-1 (got {prompt.shape}); "
            "the per-layer cache index cannot diverge per row")
    if target_model.window is not None or draft_model.window is not None:
        raise ValueError(
            "sliding-window (rolling-cache) models cannot rewind "
            "rejected proposals; use models.generate")
    if draft_model.vocab_size != target_model.vocab_size:
        raise ValueError("draft and target vocab sizes differ")
    stats = {"rounds": 0, "draft_accepted": 0, "tokens": 0}
    if steps <= 0:
        return (prompt, stats) if return_stats else prompt
    P = prompt.shape[1]
    # Same bound as models.generate: the final token is never fed.
    for m, name in ((target_model, "target"), (draft_model, "draft")):
        if P + steps - 1 > m.max_len:
            raise ValueError(
                f"prompt+steps-1={P + steps - 1} exceeds {name} "
                f"max_len={m.max_len}")

    # chunked_prefill=True: the S>1-onto-non-empty-cache verify path.
    # The PREFILL itself runs through the cp=False clone so prompt
    # numerics are identical to models.generate's one-pass prefill.
    tgt = target_model.clone(decode=True, chunked_prefill=True)
    tgt_pre = target_model.clone(decode=True, chunked_prefill=False)
    drf = draft_model.clone(decode=True, chunked_prefill=True)
    drf_pre = draft_model.clone(decode=True, chunked_prefill=False)

    t_cache = _zeros_cache(tgt, 1, prompt.dtype)
    d_cache = _zeros_cache(drf, 1, prompt.dtype)
    tl, t_cache = _jitted_step(tgt_pre, "last")(
        target_params, t_cache, prompt)
    d_cache = _jitted_step(drf_pre, "advance")(
        draft_params, d_cache, prompt)
    pending = jnp.argmax(tl[:, -1], axis=-1).astype(prompt.dtype)

    draft_tick = _jitted_step(drf, "last")
    target_tick = _jitted_step(tgt, "last")

    out = [int(pending[0])]
    consumed = P          # tokens whose K/V both caches hold
    max_fill = min(target_model.max_len, draft_model.max_len)
    while len(out) < steps:
        # Verify appends k_eff+1 entries; keep them within the cache.
        k_eff = min(k, steps - len(out), max_fill - consumed - 1)
        if k_eff < 1:
            # Cache nearly full: finish with plain target ticks (the
            # final token never needs to be fed).
            while len(out) < steps:
                tl, t_cache = target_tick(
                    target_params, t_cache, pending[:, None])
                pending = jnp.argmax(tl[:, -1], axis=-1).astype(
                    prompt.dtype)
                out.append(int(pending[0]))
                consumed += 1
            break
        # Draft proposes k_eff tokens, one tick each, starting from
        # the pending (not-yet-fed) token. `cur` stays a DEVICE array
        # across the chain — no host sync until the whole round's
        # proposals are needed for the acceptance comparison.
        dev_proposals = []
        cur = pending[:, None]
        for _ in range(k_eff):
            dl, d_cache = draft_tick(draft_params, d_cache, cur)
            cur = jnp.argmax(dl[:, -1:], axis=-1).astype(prompt.dtype)
            dev_proposals.append(cur)
        proposals = [int(c[0, 0]) for c in dev_proposals]
        # Target verifies the whole round in one forward: feeding
        # [pending, p_1..p_k] yields its greedy choice AFTER each.
        block = jnp.asarray([[int(pending[0])] + proposals],
                            prompt.dtype)
        tl, t_cache = _jitted_step(tgt, "all")(
            target_params, t_cache, block)
        greedy = np.asarray(jnp.argmax(tl[0], axis=-1))  # [k_eff+1]
        a = 0
        while a < k_eff and int(greedy[a]) == proposals[a]:
            a += 1
        # Accept p_1..p_a plus the target's own token (a == k_eff:
        # every proposal matched and greedy[k_eff] is the free bonus).
        new = proposals[:a] + [int(greedy[a])]
        out.extend(new)
        stats["rounds"] += 1
        stats["draft_accepted"] += a
        consumed += 1 + a      # pending + accepted proposals
        pending = jnp.asarray([new[-1]], prompt.dtype)
        if a == k_eff:
            # Full acceptance: p_k entered the TARGET cache via the
            # verify block but was never fed to the draft (its ticks
            # stop at p_{k-1}), so the draft cache lacks position
            # consumed-1 — write it before the forward rewind admits
            # that slot.
            d_cache = _jitted_step(drf, "advance")(
                draft_params, d_cache,
                jnp.asarray([[proposals[-1]]], prompt.dtype))
        t_cache = _rewind(t_cache, consumed)
        d_cache = _rewind(d_cache, consumed)

    tokens = jnp.concatenate(
        [prompt, jnp.asarray([out[:steps]], prompt.dtype)], axis=1)
    stats["tokens"] = len(out[:steps])
    if return_stats:
        return tokens, stats
    return tokens
