"""Stall detection.

Parity with the reference's `CheckForStalledTensors`
(`horovod/tensorflow/mpi_ops.cc:1150-1193`, invoked every 60 s from the
background loop at `:1446-1451`, threshold `STALL_WARNING_TIME = 60 s`,
`:228`): warn — don't kill — when a collective has been pending longer
than the threshold, naming the op. In the reference a stall means some
ranks never submitted a tensor (deadlock across ranks); in the TPU build
it means a dispatched collective (or a multi-controller rendezvous) has
not completed — e.g. a peer process died, which on TPU pods otherwise
surfaces only as a hang.
"""

from __future__ import annotations

import sys
import threading
import time

from horovod_tpu.analysis import lockcheck


class StallMonitor:
    def __init__(self, warning_time_s: float = 60.0,
                 check_every_s: float = 10.0, native=None):
        # State the (idempotent) stop() touches is defined FIRST: a
        # partially-constructed monitor whose stop() is called from a
        # finally block must not AttributeError (the stop-before-start
        # race).
        self._thread = None
        self._stop = threading.Event()
        self._stopped = False
        self._lock = lockcheck.register(
            "StallMonitor._lock", threading.Lock())
        # Delegate to the C++ detector (control_plane.cc) when loaded;
        # it runs its own sweep thread.
        self._native = None
        if native is not None:
            try:
                native.stall_configure(warning_time_s, check_every_s)
                native.stall_start_thread()
                self._native = native
            # hvd: disable=HVD006(the C++ control plane is optional — ANY fault probing it degrades to the Python sweep, never fails init)
            except Exception:
                self._native = None
        self._warning_time = warning_time_s
        self._check_every = check_every_s
        self._pending = {}   # name -> start timestamp
        self._warned = set()
        if self._native is None:
            self._thread = threading.Thread(
                target=self._loop, name="hvd-stall-monitor", daemon=True)
            self._thread.start()

    def begin(self, name: str):
        if self._native is not None:
            self._native.stall_begin(name)
            return
        with self._lock:
            self._pending[name] = time.time()

    def end(self, name: str):
        if self._native is not None:
            self._native.stall_end(name)
            return
        with self._lock:
            self._pending.pop(name, None)
            self._warned.discard(name)

    def check_once(self, now=None):
        """One stall sweep; returns newly-stalled op names (warn-once,
        like the reference). `now` overrides the clock for tests and is
        honored only by the pure-Python backend; on the native backend
        the C++ sweep thread may consume a stall first — programmatic
        polling should use a large `check_every_s` (as the tests do) or
        the Python backend.
        """
        if self._native is not None:
            return self._record_stalls(self._native.stall_check())
        now = now if now is not None else time.time()
        stalled = []
        with self._lock:
            for name, t0 in self._pending.items():
                if now - t0 > self._warning_time and name not in self._warned:
                    stalled.append(name)
                    self._warned.add(name)
        self._record_stalls(stalled)
        if stalled:
            # Message shape follows mpi_ops.cc:1166-1186.
            sys.stderr.write(
                "WARNING: One or more tensors were submitted to be reduced, "
                "gathered or broadcasted by subset of ranks and are waiting "
                "for remainder of ranks for more than %d seconds. This may "
                "indicate that different ranks are trying to submit "
                "different tensors or that only subset of ranks is "
                "submitting tensors, which will cause deadlock.\n"
                "Stalled ops: %s\n" % (int(self._warning_time),
                                       ", ".join(stalled)))
        return stalled

    def _record_stalls(self, stalled):
        """Beyond the stderr warning, each newly-stalled op now lands
        in the observability plane (docs/observability.md): the
        ``hvd_resilience_stalls_total`` counter and one structured
        event per op — a stall is exactly the discrete incident
        signal the event log exists for.

        Coverage caveat: with the NATIVE control plane loaded the C++
        sweep thread owns the periodic check and warns on stderr
        directly — it never passes through here, so on that backend
        only programmatic `check_once()` polls reach the counter/
        event log (the pure-Python sweep, the in-process default,
        records everything). Routing the C++ sweep through the plane
        needs a native->Python callback; out of scope here."""
        if stalled:
            from horovod_tpu.obs import catalog as _obs_catalog
            from horovod_tpu.obs import events as _events
            from horovod_tpu.obs import flightrec as _flightrec
            from horovod_tpu.obs import straggler as _straggler
            _obs_catalog.resilience_metrics()["stalls"].inc(
                len(stalled))
            # The straggler link (obs/straggler.py): a stall warning
            # arrives with the newest cross-rank attribution attached
            # — "serving_tick_41 stalled" plus "rank 5 has been 3x
            # slower than the fleet" is an actionable incident line;
            # either alone is a mystery.
            rep = _straggler.last_report()
            extra = ({"straggler": rep} if rep else {})
            for name in stalled:
                _events.emit(
                    "stall", op=name,
                    threshold_s=self._warning_time, **extra)
            # A stall trip is a flight-recorder trigger (no-op unless
            # HVD_FLIGHT_DIR is set): the bundle captures the pending
            # ops, the in-flight requests and the metric state the
            # post-mortem needs.
            _flightrec.trigger("stall", ops=list(stalled),
                               threshold_s=self._warning_time)
        return stalled

    def _loop(self):
        while not self._stop.wait(self._check_every):
            self.check_once()

    def stop(self, timeout: float = 5.0):
        """Stop the sweep and JOIN its thread so no warning can land
        after stop() returns (engines stop their monitor at shutdown
        and then tear down the state the sweep reads). Idempotent:
        double-stop and stop-before-start are both no-op-safe — the
        flag is claimed under the lock, so concurrent stops perform
        the native stop / join exactly once."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        if self._native is not None:
            self._native.stall_stop_thread()
            return
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)
