"""Horovod Timeline — Chrome-trace (chrome://tracing) profiler.

Parity with the reference timeline (SURVEY §5.1; `timeline.h`/`timeline.cc`):
tensors are modeled as trace *processes* (pid = interned tensor index,
`timeline.cc:59-76`); events are `B`/`E` duration pairs and `X` instants
(`timeline.cc:78-92`); a per-tensor state machine
{UNKNOWN, NEGOTIATING, TOP_LEVEL, ACTIVITY} guards transitions
(`timeline.h:37-42`); writes flush on a ~1 s cadence (`timeline.h:35`).
Enabled via `HOROVOD_TIMELINE=/path/file.json` (`mpi_ops.cc:1272-1275`).

Device-side profiling is deferred to `jax.profiler` (the XLA/TPU
profiler); this timeline covers the host-side schedule — negotiation is
compile-time under SPMD, so NEGOTIATING brackets validation + dispatch.

When the native control plane is available the same format is written by
the C++ writer (`horovod_tpu/native/control_plane.cc`); this Python
implementation is the in-process default and fallback.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

UNKNOWN, NEGOTIATING, TOP_LEVEL, ACTIVITY = range(4)

FLUSH_INTERVAL_S = 1.0  # timeline.h:35


class Timeline:
    def __init__(self, path: str, native=None):
        # Prefer the C++ writer (horovod_tpu/native/control_plane.cc) when
        # the control plane is loaded; same format, off the Python lock.
        self._native = None
        if native is not None:
            try:
                if native.timeline_start(path) == 0:
                    self._native = native
            # hvd: disable=HVD006(the C++ writer is optional — ANY probe fault falls back to the Python writer, never fails tracing)
            except Exception:
                self._native = None
        self._path = path
        self._lock = threading.Lock()
        self._pids = {}           # tensor name -> pid
        self._states = {}         # tensor name -> state
        self._events = []
        self._last_flush = time.time()
        self._start = time.time()
        self._closed = False
        if self._native is None:
            try:
                # Truncate/create the file with the JSON array opener.
                with open(self._path, "w") as f:
                    f.write("[\n")
            except OSError as e:
                # Warn and disable, don't fail training — the reference's
                # behavior on an unwritable timeline (timeline.cc:32-34,
                # 100-103).
                import sys
                sys.stderr.write(
                    f"WARNING: Error opening the Horovod Timeline file "
                    f"{self._path!r}, will not write a timeline: {e}\n")
                self._closed = True

    def _ts_us(self) -> int:
        return int((time.time() - self._start) * 1e6)

    def _pid(self, name: str) -> int:
        pid = self._pids.get(name)
        if pid is None:
            pid = len(self._pids)
            self._pids[name] = pid
            self._events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": name},
            })
        return pid

    def _emit(self, ph: str, name: str, pid: int, **kw):
        ev = {"ph": ph, "name": name, "pid": pid, "ts": self._ts_us()}
        ev.update(kw)
        self._events.append(ev)

    def record(self, tensor: str, phase: str, activity: Optional[str] = None):
        """Record a phase transition for `tensor`.

        phase ∈ {NEGOTIATING, TOP_LEVEL, DONE}; `activity` opens a nested
        activity span (the reference's ACTIVITY_START_ALL vocabulary:
        ALLREDUCE, ALLGATHER, BCAST, MEMCPY_IN_FUSION_BUFFER, ...).
        """
        if self._native is not None:
            if not self._closed:
                self._native.timeline_record(tensor, phase, activity)
            return
        with self._lock:
            if self._closed:
                return
            pid = self._pid(tensor)
            state = self._states.get(tensor, UNKNOWN)
            if phase == "NEGOTIATING":
                self._emit("B", "NEGOTIATE", pid)
                self._states[tensor] = NEGOTIATING
            elif phase == "TOP_LEVEL":
                if state == NEGOTIATING:
                    self._emit("E", "NEGOTIATE", pid)
                self._emit("B", tensor, pid)
                self._states[tensor] = TOP_LEVEL
                if activity:
                    self._emit("B", activity, pid)
                    self._states[tensor] = ACTIVITY
            elif phase == "DONE":
                if state == ACTIVITY:
                    self._emit("E", "", pid)
                if state in (TOP_LEVEL, ACTIVITY):
                    self._emit("E", tensor, pid)
                elif state == NEGOTIATING:
                    self._emit("E", "NEGOTIATE", pid)
                self._states[tensor] = UNKNOWN
            self._maybe_flush()

    def begin_span(self, process: str, name: str,
                   args: Optional[dict] = None):
        """Open a named B span on ``process`` (interned as its own
        trace pid, like a tensor) — the request-level vocabulary the
        serving engine emits (QUEUE / PREFILL / DECODE), so every
        request renders as a distinct trace process in
        chrome://tracing. Unlike `record` there is no per-tensor state
        machine: spans pair by name via `end_span` and nest freely.
        ``args`` lands in the Chrome-trace event's ``args`` payload —
        the serving engine stamps each request's ``trace_id`` there,
        so a span in chrome://tracing links to the same request's
        event-log lines and metric exemplars (docs/observability.md).

        The native C++ writer has no generic-span verb, so spans ride
        its TOP_LEVEL/DONE tensor lifecycle (one outer process-named
        bar wrapping each span's activity bar) — same trace, slightly
        chattier nesting, and ``args`` are dropped (the Python writer
        is the tracing-fidelity path)."""
        if self._native is not None:
            if not self._closed:
                self._native.timeline_record(process, "TOP_LEVEL", name)
            return
        with self._lock:
            if self._closed:
                return
            if args:
                self._emit("B", name, self._pid(process), args=args)
            else:
                self._emit("B", name, self._pid(process))
            self._maybe_flush()

    def end_span(self, process: str, name: str):
        """Close the matching `begin_span` (see its doc)."""
        if self._native is not None:
            if not self._closed:
                self._native.timeline_record(process, "DONE", None)
            return
        with self._lock:
            if self._closed:
                return
            self._emit("E", name, self._pid(process))
            self._maybe_flush()

    def mark(self, tensor: str, name: str):
        """Instant event (`X`, timeline.cc:78-92)."""
        if self._native is not None:
            if not self._closed:
                self._native.timeline_mark(tensor, name)
            return
        with self._lock:
            if self._closed:
                return
            self._emit("X", name, self._pid(tensor), dur=0)
            self._maybe_flush()

    def _maybe_flush(self):
        if time.time() - self._last_flush >= FLUSH_INTERVAL_S:
            self._flush_locked()

    def _flush_locked(self):
        if not self._events:
            return
        try:
            with open(self._path, "a") as f:
                for ev in self._events:
                    f.write(json.dumps(ev) + ",\n")
        except OSError as e:
            # Same warn-and-disable contract as the constructor
            # (timeline.cc:32-34): a mid-run I/O failure (disk full,
            # file removed) must cost the trace, never the training
            # step or serving request that happened to trigger the
            # flush.
            import sys
            sys.stderr.write(
                f"WARNING: Error writing the Horovod Timeline file "
                f"{self._path!r}, disabling the timeline: {e}\n")
            # hvd: disable=HVD004(_flush_locked runs with self._lock held — every caller is inside a `with self._lock` block, per the name)
            self._closed = True
        self._events = []
        self._last_flush = time.time()

    def close(self):
        if self._native is not None:
            # The native writer is its own serialization point: every
            # C++ entry (Record/Mark/Stop) takes the internal mutex
            # and no-ops once Stop nulled the file, so a record racing
            # this close is SAFE without Python-side locking — the
            # unlocked `_closed` checks in record/begin/end/mark are
            # only a cheap fast-path short-circuit. The lock here just
            # keeps close() itself idempotent and `_closed` writes
            # single-writer (hvdlint HVD004).
            with self._lock:
                if not self._closed:
                    self._native.timeline_stop()
                    self._closed = True
            return
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            # Chrome tolerates a trailing comma without a closing bracket
            # (the reference also streams without closing, timeline.cc);
            # write a terminator for strict parsers.
            try:
                with open(self._path, "a") as f:
                    f.write("{}]\n")
            except OSError:
                pass  # flush already warned; close stays quiet
            self._closed = True


def start_timeline(path: str):
    """Programmatic timeline start (env-var HOROVOD_TIMELINE also works)."""
    from horovod_tpu.runtime import state as _state
    st = _state.check_initialized()
    if st.timeline is not None:
        st.timeline.close()
    st.timeline = Timeline(path, native=st.native)
    return st.timeline


def stop_timeline():
    from horovod_tpu.runtime import state as _state
    st = _state.check_initialized()
    if st.timeline is not None:
        st.timeline.close()
        st.timeline = None


def step_bracket(fn, name: str = "train_step"):
    """Wrap a jitted train step so every invocation emits a host-side
    B/E span on the HOROVOD_TIMELINE trace.

    Under SPMD the per-collective events the reference logs do not
    exist at runtime — collectives are compiled into the XLA program
    and are invisible to the host (device traces belong to
    `jax.profiler`, see docs/timeline.md). What the host CAN see, and
    what this bracket records, is the step cadence: dispatch duration,
    gaps between steps (input pipeline stalls), and how eager
    collectives interleave with the jitted hot path — all in the same
    Chrome trace. No-op overhead when no timeline is configured.
    """
    import functools

    from horovod_tpu.runtime import state as _state

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        tl = _state.global_state().timeline
        if tl is None:
            return fn(*args, **kwargs)
        tl.record(name, "TOP_LEVEL", "DISPATCH")
        try:
            return fn(*args, **kwargs)
        finally:
            tl.record(name, "DONE")

    return wrapper
