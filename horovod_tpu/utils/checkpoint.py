"""Checkpoint / resume with the reference's consistency contract.

The reference delegates serialization to TF and imposes two rules
(SURVEY §5.4): (a) only rank 0 writes, so concurrent workers cannot
corrupt the checkpoint (`README.md:79-81`, `examples/tensorflow_mnist.py:102`);
(b) on start/restore, rank-0 state is broadcast so every worker resumes
from identical weights (`horovod/tensorflow/__init__.py:93-124`).

This module keeps both rules and delegates serialization to Orbax (the
JAX-native checkpointer): `save()` is a no-op off rank 0, `restore()`
broadcasts the loaded pytree from rank 0 when requested. Multi-host
sharded checkpointing (every host writes its own shards in parallel —
something the reference cannot do) is available via ``distributed=True``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _solo_mp_options(prefix: str):
    """Orbax MultiprocessingOptions restricting sync barriers to THIS
    process. Required for the rank-0-only save path when
    `jax.distributed` is active: the default checkpointer synchronizes
    across ALL processes after the write, so a save only rank 0
    executes would park rank 0 in a barrier the other ranks never join
    — deadlock (observed with the resume example under hvdrun -np 2).
    """
    import orbax.checkpoint as ocp
    me = jax.process_index()
    return ocp.options.MultiprocessingOptions(
        primary_host=me, active_processes={me},
        barrier_sync_key_prefix=f"{prefix}{me}")


def _checkpointer(solo: bool = False):
    """Orbax pytree checkpointer (`solo`: see `_solo_mp_options`)."""
    import orbax.checkpoint as ocp
    if solo and jax.process_count() > 1:
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=_solo_mp_options("solo"))
    return ocp.PyTreeCheckpointer()


_async_state = {"ckpt": None}


def _async_checkpointer():
    """Lazily-built async pytree checkpointer (solo sync scope, like
    `_checkpointer`); one in-flight save at a time."""
    import orbax.checkpoint as ocp
    if _async_state["ckpt"] is None:
        kwargs = {}
        if jax.process_count() > 1:
            kwargs["multiprocessing_options"] = _solo_mp_options("asolo")
        _async_state["ckpt"] = ocp.AsyncCheckpointer(
            ocp.PyTreeCheckpointHandler(), **kwargs)
        import atexit
        atexit.register(_fence_swallowing)
    return _async_state["ckpt"]


def wait_pending() -> None:
    """Block until any in-flight async save commits (no-op otherwise).

    STRICT fence: a failed background write (ENOSPC, permissions)
    re-raises here — this is the user's success signal for the last
    save, so it must not report success silently. Call it from normal
    program flow (end of training, before reading the directory).
    `hvd.shutdown()` and atexit use the swallowing variant instead,
    because teardown must proceed (the native control plane still has
    to close or peers hang) and Orbax finalization cannot schedule
    executor work once interpreter shutdown has begun — a save still
    in flight when the process simply falls off main() may be
    discarded (Orbax commits atomically: the directory either appears
    complete or not at all).
    """
    if _async_state["ckpt"] is not None:
        _async_state["ckpt"].wait_until_finished()


def _fence_swallowing() -> None:
    """`wait_pending` for teardown paths: never raises."""
    try:
        wait_pending()
    except Exception as e:  # noqa: BLE001 — shutdown must proceed
        import sys
        print(f"horovod_tpu: async checkpoint fence failed ({e!r}); "
              f"the last save may not have committed — call "
              f"wait_pending() before exiting to surface this",
              file=sys.stderr)


def save(path: str, state: Any, *, force: bool = True,
         distributed: bool = False, block: bool = True) -> bool:
    """Write `state` (any pytree of arrays) to `path`.

    Rank-0-only unless ``distributed`` (Orbax multi-host mode where all
    processes participate in writing their own shards). Returns True if
    this process wrote (or started writing).

    ``block=False``: async save — the write proceeds on background
    threads so the train loop keeps stepping (the standard TPU recipe:
    checkpoint IO must not stall the device). At most one save is in
    flight; a new one first waits for the previous. `wait_pending()`
    (also registered atexit) fences explicitly.
    """
    from horovod_tpu.runtime import bootstrap as bs

    if not block and distributed:
        raise NotImplementedError(
            "async distributed save is not supported: the all-process "
            "Orbax commit barrier cannot run on background threads; "
            "use block=True with distributed=True")
    if not distributed and bs.is_initialized() and bs.rank() != 0:
        return False
    state = jax.tree.map(
        lambda x: np.asarray(x) if not distributed else x, state)
    if not block and not distributed:
        ckpt = _async_checkpointer()
        ckpt.wait_until_finished()
        ckpt.save(os.path.abspath(path), state, force=force)
        return True
    # The sync path must also fence any in-flight async save: an async
    # write committing AFTER a sync write to the same path would
    # silently replace the newer data with the stale save.
    wait_pending()
    _checkpointer(solo=not distributed).save(
        os.path.abspath(path), state, force=force)
    return True


def restore(path: str, *, like: Optional[Any] = None,
            broadcast: bool = False) -> Any:
    """Load the pytree at `path`.

    ``like``: optional template pytree — restored leaves adopt its
    structure/dtypes (Orbax restore_args). ``broadcast=True`` re-asserts
    the reference's resume contract by broadcasting the loaded state
    from rank 0 (meaningful in multi-controller mode where workers may
    read different files or a stale mirror).
    """
    restore_args = None
    if like is not None:
        import orbax.checkpoint as ocp
        restore_args = ocp.checkpoint_utils.construct_restore_args(like)
    # solo: every process reads the full tree independently (read-only;
    # no cross-process barriers), then `broadcast` re-synchronizes.
    restored = _checkpointer(solo=True).restore(
        os.path.abspath(path), item=like, restore_args=restore_args)
    if broadcast:
        import horovod_tpu as hvd
        restored = hvd.broadcast_global_variables(restored, 0)
    return restored


def _step_entries(directory: str):
    """Sorted [(step, dirname)] for step checkpoint subdirectories
    (`step_00000100`-style or plain ints like `100`)."""
    if not os.path.isdir(directory):
        return []
    entries = []
    for name in os.listdir(directory):
        if not os.path.isdir(os.path.join(directory, name)):
            continue
        if name.isdigit():
            entries.append((int(name), name))
        elif name.startswith("step_") and name[5:].isdigit():
            entries.append((int(name[5:]), name))
    return sorted(entries)


def latest_step(directory: str) -> Optional[int]:
    """Highest step checkpoint under `directory`, or None — the
    resume-discovery helper."""
    entries = _step_entries(directory)
    return entries[-1][0] if entries else None


def save_step(directory: str, step: int, state: Any, *,
              keep: int = 3, block: bool = True) -> bool:
    """`save()` into `directory/step_{step:08d}`, then prune the lowest
    steps down to `keep` entries — never the one just written (rank 0
    only). ``block=False`` saves asynchronously; Orbax commits the
    directory atomically, so pruning only ever sees finished steps —
    which also means the in-flight save isn't counted yet and the
    directory can transiently hold `keep + 1` entries until the next
    call (or `wait_pending()` + another `save_step`) prunes it."""
    current = f"step_{step:08d}"
    wrote = save(os.path.join(directory, current), state, block=block)
    if wrote and keep > 0:
        import shutil
        entries = _step_entries(directory)
        candidates = [n for _, n in entries if n != current]
        excess = len(entries) - keep
        for name in candidates[:max(0, excess)]:
            shutil.rmtree(os.path.join(directory, name),
                          ignore_errors=True)
    return wrote


def restore_latest(directory: str, *, like: Optional[Any] = None,
                   broadcast: bool = False) -> Optional[Any]:
    """Restore the highest step under `directory`, or None if empty."""
    entries = _step_entries(directory)
    if not entries:
        return None
    return restore(os.path.join(directory, entries[-1][1]),
                   like=like, broadcast=broadcast)
