"""Checkpoint / resume with the reference's consistency contract.

The reference delegates serialization to TF and imposes two rules
(SURVEY §5.4): (a) only rank 0 writes, so concurrent workers cannot
corrupt the checkpoint (`README.md:79-81`, `examples/tensorflow_mnist.py:102`);
(b) on start/restore, rank-0 state is broadcast so every worker resumes
from identical weights (`horovod/tensorflow/__init__.py:93-124`).

This module keeps both rules and delegates serialization to Orbax (the
JAX-native checkpointer): `save()` is a no-op off rank 0, `restore()`
broadcasts the loaded pytree from rank 0 when requested. Multi-host
sharded checkpointing (every host writes its own shards in parallel —
something the reference cannot do) is available via ``distributed=True``.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Optional, Tuple, Union

import jax
import numpy as np

from horovod_tpu.resilience import chaos
from horovod_tpu.resilience.retry import RetryPolicy, default_io_policy


class CheckpointError(RuntimeError):
    """Base class for checkpoint load/store failures."""


class CheckpointNotFoundError(CheckpointError):
    """`restore()` was pointed at a path with no checkpoint directory."""


class CheckpointCorruptError(CheckpointError):
    """The checkpoint directory exists but cannot be read back — a
    partial write (the process died mid-save), filesystem damage, or a
    template mismatch. The original Orbax error is chained."""


def _solo_mp_options(prefix: str):
    """Orbax MultiprocessingOptions restricting sync barriers to THIS
    process. Required for the rank-0-only save path when
    `jax.distributed` is active: the default checkpointer synchronizes
    across ALL processes after the write, so a save only rank 0
    executes would park rank 0 in a barrier the other ranks never join
    — deadlock (observed with the resume example under hvdrun -np 2).
    """
    import orbax.checkpoint as ocp
    me = jax.process_index()
    return ocp.options.MultiprocessingOptions(
        primary_host=me, active_processes={me},
        barrier_sync_key_prefix=f"{prefix}{me}")


def _checkpointer(solo: bool = False):
    """Orbax pytree checkpointer (`solo`: see `_solo_mp_options`)."""
    import orbax.checkpoint as ocp
    if solo and jax.process_count() > 1:
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=_solo_mp_options("solo"))
    return ocp.PyTreeCheckpointer()


_async_state = {"ckpt": None}


def _async_checkpointer():
    """Lazily-built async pytree checkpointer (solo sync scope, like
    `_checkpointer`); one in-flight save at a time."""
    import orbax.checkpoint as ocp
    if _async_state["ckpt"] is None:
        kwargs = {}
        if jax.process_count() > 1:
            kwargs["multiprocessing_options"] = _solo_mp_options("asolo")
        _async_state["ckpt"] = ocp.AsyncCheckpointer(
            ocp.PyTreeCheckpointHandler(), **kwargs)
        import atexit
        atexit.register(_fence_swallowing)
    return _async_state["ckpt"]


def wait_pending() -> None:
    """Block until any in-flight async save commits (no-op otherwise).

    STRICT fence: a failed background write (ENOSPC, permissions)
    re-raises here — this is the user's success signal for the last
    save, so it must not report success silently. Call it from normal
    program flow (end of training, before reading the directory).
    `hvd.shutdown()` and atexit use the swallowing variant instead,
    because teardown must proceed (the native control plane still has
    to close or peers hang) and Orbax finalization cannot schedule
    executor work once interpreter shutdown has begun — a save still
    in flight when the process simply falls off main() may be
    discarded (Orbax commits atomically: the directory either appears
    complete or not at all).
    """
    if _async_state["ckpt"] is not None:
        _async_state["ckpt"].wait_until_finished()


def _fence_swallowing() -> None:
    """`wait_pending` for teardown paths: never raises."""
    try:
        wait_pending()
    # hvd: disable=HVD006(teardown fence: shutdown must proceed past any Orbax finalization fault; the warning below surfaces it)
    except Exception as e:  # noqa: BLE001 — shutdown must proceed
        import sys
        print(f"horovod_tpu: async checkpoint fence failed ({e!r}); "
              f"the last save may not have committed — call "
              f"wait_pending() before exiting to surface this",
              file=sys.stderr)


def save(path: str, state: Any, *, force: bool = True,
         distributed: bool = False, block: bool = True,
         retry: Optional[RetryPolicy] = None) -> bool:
    """Write `state` (any pytree of arrays) to `path`.

    Rank-0-only unless ``distributed`` (Orbax multi-host mode where all
    processes participate in writing their own shards). Returns True if
    this process wrote (or started writing).

    ``block=False``: async save — the write proceeds on background
    threads so the train loop keeps stepping (the standard TPU recipe:
    checkpoint IO must not stall the device). At most one save is in
    flight; a new one first waits for the previous. `wait_pending()`
    (also registered atexit) fences explicitly.

    Transient write failures (`OSError`, injected `ChaosError`s at the
    ``ckpt_write_fail`` site) are retried with exponential backoff
    under ``retry`` — default `default_io_policy()` (3 attempts,
    ``HVD_IO_RETRIES`` overrides). All attempts exhausted raises
    `resilience.retry.RetryError`. Ranks cannot diverge: ranks other
    than 0 return before the write, and the ``distributed`` path is
    NEVER retried — it is a collective write with cross-process
    barriers, and one rank re-entering it alone (a rank-local 5xx)
    would park every peer in a mismatched barrier; a distributed save
    fails fast instead.

    Async caveat: with ``block=False`` the policy covers the
    *scheduling* of the save (fencing the previous one included); a
    failure in the background commit itself is NOT retried — it
    surfaces at the next fence (`wait_pending()`, the next save, or
    atexit), the same place async failures always surface. Runs that
    need the full retry guarantee for a particular save (emergency
    checkpoints) use ``block=True``.
    """
    from horovod_tpu.runtime import bootstrap as bs

    if not block and distributed:
        raise NotImplementedError(
            "async distributed save is not supported: the all-process "
            "Orbax commit barrier cannot run on background threads; "
            "use block=True with distributed=True")
    if not distributed and bs.is_initialized() and bs.rank() != 0:
        return False
    state = jax.tree.map(
        lambda x: np.asarray(x) if not distributed else x, state)
    policy = retry if retry is not None else default_io_policy()
    if not block and not distributed:
        ckpt = _async_checkpointer()
        # Fence the PREVIOUS async save OUTSIDE the retry: a failure
        # re-raised here belongs to that save and must propagate to
        # the caller (the wait_pending contract) — the retry loop
        # must not consume it as this save's transient error.
        ckpt.wait_until_finished()

        def _schedule():
            if chaos.fires("ckpt_write_fail"):
                raise chaos.ChaosError(
                    f"injected checkpoint write failure at {path} "
                    f"(site ckpt_write_fail)")
            ckpt.save(os.path.abspath(path), state, force=force)
        policy.call(_schedule)
        return True
    # The sync path must also fence any in-flight async save: an async
    # write committing AFTER a sync write to the same path would
    # silently replace the newer data with the stale save.
    wait_pending()

    def _write():
        if chaos.fires("ckpt_write_fail"):
            raise chaos.ChaosError(
                f"injected checkpoint write failure at {path} "
                f"(site ckpt_write_fail)")
        _checkpointer(solo=not distributed).save(
            os.path.abspath(path), state, force=force)
    if distributed:
        # Collective multi-host write: retrying on a rank-LOCAL error
        # would re-enter Orbax's cross-process barriers on one rank
        # only — the pod hangs instead of failing fast (see
        # docstring). Raw error propagates, no retry.
        _write()
    else:
        policy.call(_write)
    return True


def restore(path: str, *, like: Optional[Any] = None,
            broadcast: bool = False) -> Any:
    """Load the pytree at `path`.

    ``like``: optional template pytree — restored leaves adopt its
    structure/dtypes (Orbax restore_args). ``broadcast=True`` re-asserts
    the reference's resume contract by broadcasting the loaded state
    from rank 0 (meaningful in multi-controller mode where workers may
    read different files or a stale mirror).

    Failure surface (instead of a raw Orbax traceback): a missing
    directory raises `CheckpointNotFoundError`; a directory that
    exists but cannot be read back (partial write, corruption,
    template mismatch) raises `CheckpointCorruptError` with the path
    named and the underlying error chained. `restore_latest` catches
    both and falls back to the previous step.
    """
    apath = os.path.abspath(path)
    if not os.path.isdir(apath):
        raise CheckpointNotFoundError(
            f"no checkpoint directory at {apath}")
    restore_args = None
    if like is not None:
        import orbax.checkpoint as ocp
        restore_args = ocp.checkpoint_utils.construct_restore_args(like)
    # solo: every process reads the full tree independently (read-only;
    # no cross-process barriers), then `broadcast` re-synchronizes.
    try:
        restored = _checkpointer(solo=True).restore(
            apath, item=like, restore_args=restore_args)
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint at {apath} is unreadable — partial write, "
            f"corruption, or a template mismatch ({e!r})") from e
    if broadcast:
        import horovod_tpu as hvd
        restored = hvd.broadcast_global_variables(restored, 0)
    return restored


def _step_entries(directory: str):
    """Sorted [(step, dirname)] for step checkpoint subdirectories
    (`step_00000100`-style or plain ints like `100`)."""
    if not os.path.isdir(directory):
        return []
    entries = []
    for name in os.listdir(directory):
        if not os.path.isdir(os.path.join(directory, name)):
            continue
        if name.isdigit():
            entries.append((int(name), name))
        elif name.startswith("step_") and name[5:].isdigit():
            entries.append((int(name[5:]), name))
    return sorted(entries)


def latest_step(directory: str) -> Optional[int]:
    """Highest step checkpoint under `directory`, or None — the
    resume-discovery helper."""
    entries = _step_entries(directory)
    return entries[-1][0] if entries else None


def _looks_committed(path: str) -> bool:
    """Cheap commit probe for the retention GC: every write path ends
    in an atomic directory appearance (our tmp+rename for sync saves,
    Orbax's own commit rename for async), and committed Orbax trees
    carry a `_CHECKPOINT_METADATA` marker — so a discoverable step
    without the marker is externally damaged (filesystem ate blocks,
    a manually gutted dir) and must never count as the restorable
    entry the GC is obliged to preserve."""
    return os.path.isfile(os.path.join(path, "_CHECKPOINT_METADATA"))


def _aux_path(directory: str, name: str) -> str:
    """Sidecar path for a step's auxiliary snapshot JSON (the
    data-pipeline cursor + host RNG + guard state of a TrainSnapshot,
    `resilience/elastic.py`)."""
    return os.path.join(directory, name + ".aux.json")


def _write_aux(directory: str, name: str, aux: Any):
    """Atomically (tmp + rename) write the aux sidecar. Written BEFORE
    the step directory becomes discoverable, so any discoverable step
    saved with aux has its sidecar on disk; a crash in the window
    between sidecar and state commit leaves only a harmless orphan
    that the next save of the same step overwrites (and pruning
    removes)."""
    import json
    path = _aux_path(directory, name)
    tmp = path + ".tmp"
    os.makedirs(directory, exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(aux, f)
    os.replace(tmp, path)


def load_step_aux(directory: str, step: int
                  ) -> Tuple[Optional[Any], Optional[str]]:
    """Read the aux sidecar saved alongside step `step`.

    Returns ``(aux, None)`` on success, ``(None, reason)`` when the
    sidecar is missing or unreadable — the caller decides how loud the
    degraded path is (`ElasticTrainer.resume` falls back to the epoch
    boundary and emits a `cursor_fallbacks` metric + event)."""
    import json
    names = [n for s, n in _step_entries(directory) if s == step]
    if not names:
        return None, f"no step {step} under {directory}"
    path = _aux_path(directory, names[-1])
    if not os.path.isfile(path):
        return None, f"aux sidecar missing: {path}"
    try:
        with open(path) as f:
            return json.load(f), None
    except (OSError, ValueError) as e:
        return None, f"aux sidecar unreadable ({e!r}): {path}"


def save_step(directory: str, step: int, state: Any, *,
              keep: Optional[int] = None, block: bool = True,
              retry: Optional[RetryPolicy] = None,
              aux: Optional[Any] = None) -> bool:
    """`save()` into `directory/step_{step:08d}`, then prune the lowest
    steps down to `keep` entries (rank 0 only). ``keep=None`` reads
    the registered ``HVD_CKPT_KEEP`` knob (default 0 = keep all; the
    GC is opt-in because deleting history is the one thing a
    checkpoint layer must never surprise anyone with). The GC never
    deletes the step just written, nor the newest COMMITTED step —
    the one `restore_latest` would pick right now — so an async save
    still in flight can't leave the directory restorable-empty if the
    process dies before its commit lands. Pruned steps take their aux
    sidecars with them.

    ``aux``: optional JSON-able sidecar (`<step>.aux.json`, read back
    by `load_step_aux`) written atomically BEFORE the step becomes
    discoverable — the TrainSnapshot home for the data-pipeline
    cursor, host RNG, and guard state (`docs/resilience.md` "Exact
    resume"). Sidecar, not pytree leaf: the cursor must survive a
    `like=` template that doesn't mention it, and a corrupt cursor
    must degrade to an epoch-boundary resume without poisoning the
    model restore.

    ``block=False`` saves asynchronously; Orbax commits the directory
    atomically, so pruning only ever sees finished steps — which also
    means the in-flight save isn't counted yet and the directory can
    transiently hold `keep + 1` entries until the next call (or
    `wait_pending()` + another `save_step`) prunes it.

    The sync path is atomic end-to-end: the tree is written into a
    hidden ``.tmp.step_*`` staging directory (invisible to step
    discovery) and renamed into place only after the write fully
    committed — a process killed mid-save (the ``ckpt_kill`` chaos
    site injects exactly that) leaves either the previous checkpoint
    set or the complete new one, never a discoverable half-written
    step. (The async path relies on Orbax's own atomic directory
    commit.)"""
    if keep is None:
        from horovod_tpu.runtime.config import env_int
        keep = env_int("HVD_CKPT_KEEP", 0)
    current = f"step_{step:08d}"
    final = os.path.join(directory, current)
    if block:
        import shutil
        tmp = os.path.join(directory, f".tmp.{current}")
        shutil.rmtree(tmp, ignore_errors=True)  # stale staging dir
        wrote = save(tmp, state, block=True, retry=retry)
        if wrote:
            if chaos.fires("ckpt_kill"):
                # Simulated mid-save process death: the staged tree
                # exists, the rename never happens — discovery sees
                # only the previous steps (the crash-restart
                # equivalence harness's kill-during-save scenario).
                raise chaos.ChaosError(
                    f"injected process kill mid-save at {final} "
                    f"(site ckpt_kill)")
            if aux is not None:
                _write_aux(directory, current, aux)
            if os.path.isdir(final):
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
    else:
        wrote = save(final, state, block=False, retry=retry)
        if wrote and aux is not None:
            # Sidecar lands before Orbax's background commit renames
            # the step into discoverability — same ordering contract
            # as the sync path.
            _write_aux(directory, current, aux)
    if wrote and keep > 0:
        import shutil
        entries = _step_entries(directory)
        protected = {current}
        committed = [n for _, n in entries
                     if _looks_committed(os.path.join(directory, n))]
        if committed:
            protected.add(committed[-1])
        candidates = [n for _, n in entries if n not in protected]
        excess = len(entries) - keep
        for name in candidates[:max(0, excess)]:
            shutil.rmtree(os.path.join(directory, name),
                          ignore_errors=True)
            try:
                os.unlink(_aux_path(directory, name))
            except OSError:
                pass
    return wrote


def restore_latest(directory: str, *, like: Optional[Any] = None,
                   broadcast: bool = False,
                   with_step: bool = False
                   ) -> Union[None, Any, Tuple[Any, int]]:
    """Restore the highest GOOD step under `directory`, or None if
    empty.

    Latest-good discovery: when the newest step directory is a partial
    write or corrupt (`CheckpointCorruptError` — e.g. the process was
    preempted mid-save without the atomic rename, or the filesystem
    ate blocks), it is skipped with a warning and the previous step is
    tried, newest to oldest. Only when *every* step fails does the
    last `CheckpointCorruptError` propagate — silent loss of the whole
    directory would hide real damage.

    ``with_step=True`` returns ``(state, step)`` so resume logic knows
    which step actually loaded (it may not be the highest on disk).
    """
    entries = _step_entries(directory)
    if not entries:
        return None
    last_err: Optional[CheckpointError] = None
    restored = None
    found_step = None
    for step, name in reversed(entries):
        try:
            # broadcast deliberately NOT passed through: the per-step
            # read must stay collective-free, because ranks can
            # disagree on WHICH step is corrupt (rank-local FS damage,
            # a stale mirror) — a collective inside this loop would
            # pair mismatched broadcasts across ranks and hang the
            # pod. Every rank broadcasts exactly once below instead.
            restored = restore(os.path.join(directory, name),
                               like=like, broadcast=False)
            found_step = step
            break
        except CheckpointError as e:
            sys.stderr.write(
                f"horovod_tpu: skipping bad checkpoint "
                f"{os.path.join(directory, name)} ({e}); falling back "
                f"to the previous step\n")
            last_err = e
    if found_step is None:
        raise CheckpointCorruptError(
            f"no restorable checkpoint among {len(entries)} step(s) "
            f"in {directory}; newest failure chained") from last_err
    if broadcast:
        # Rank-0's tree wins even if this rank fell back to an older
        # step than rank 0 did (the returned step is then the LOCAL
        # discovery; the state is rank 0's — the reference's resume
        # contract).
        import horovod_tpu as hvd
        restored = hvd.broadcast_global_variables(restored, 0)
    return (restored, found_step) if with_step else restored
