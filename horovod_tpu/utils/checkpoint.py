"""Checkpoint / resume with the reference's consistency contract.

The reference delegates serialization to TF and imposes two rules
(SURVEY §5.4): (a) only rank 0 writes, so concurrent workers cannot
corrupt the checkpoint (`README.md:79-81`, `examples/tensorflow_mnist.py:102`);
(b) on start/restore, rank-0 state is broadcast so every worker resumes
from identical weights (`horovod/tensorflow/__init__.py:93-124`).

This module keeps both rules and delegates serialization to Orbax (the
JAX-native checkpointer): `save()` is a no-op off rank 0, `restore()`
broadcasts the loaded pytree from rank 0 when requested. Multi-host
sharded checkpointing (every host writes its own shards in parallel —
something the reference cannot do) is available via ``distributed=True``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, *, force: bool = True,
         distributed: bool = False) -> bool:
    """Write `state` (any pytree of arrays) to `path`.

    Rank-0-only unless ``distributed`` (Orbax multi-host mode where all
    processes participate in writing their own shards). Returns True if
    this process wrote.
    """
    from horovod_tpu.runtime import bootstrap as bs

    if not distributed and bs.is_initialized() and bs.rank() != 0:
        return False
    state = jax.tree.map(
        lambda x: np.asarray(x) if not distributed else x, state)
    _checkpointer().save(os.path.abspath(path), state, force=force)
    return True


def restore(path: str, *, like: Optional[Any] = None,
            broadcast: bool = False) -> Any:
    """Load the pytree at `path`.

    ``like``: optional template pytree — restored leaves adopt its
    structure/dtypes (Orbax restore_args). ``broadcast=True`` re-asserts
    the reference's resume contract by broadcasting the loaded state
    from rank 0 (meaningful in multi-controller mode where workers may
    read different files or a stale mirror).
    """
    restored = _checkpointer().restore(os.path.abspath(path),
                                       item=like)
    if broadcast:
        import horovod_tpu as hvd
        restored = hvd.broadcast_global_variables(restored, 0)
    return restored


def latest_step(directory: str) -> Optional[int]:
    """Highest numeric subdirectory of `directory` (step_000100-style or
    plain ints), or None — the resume-discovery helper."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        digits = name.split("_")[-1]
        if digits.isdigit():
            steps.append(int(digits))
    return max(steps) if steps else None


def save_step(directory: str, step: int, state: Any, *,
              keep: int = 3) -> bool:
    """`save()` into `directory/step_{step:08d}`, pruning old steps
    beyond `keep` (rank 0 only)."""
    from horovod_tpu.runtime import bootstrap as bs

    wrote = save(os.path.join(directory, f"step_{step:08d}"), state)
    if wrote and keep > 0:
        kept = sorted(
            (n for n in os.listdir(directory)
             if n.startswith("step_") and n.split("_")[-1].isdigit()),
            key=lambda n: int(n.split("_")[-1]))
        for name in kept[:-keep]:
            import shutil
            shutil.rmtree(os.path.join(directory, name),
                          ignore_errors=True)
    return wrote


def restore_latest(directory: str, *, like: Optional[Any] = None,
                   broadcast: bool = False) -> Optional[Any]:
    """Restore the highest step under `directory`, or None if empty."""
    step = latest_step(directory)
    if step is None:
        return None
    return restore(os.path.join(directory, f"step_{step:08d}"),
                   like=like, broadcast=broadcast)
