"""Overlap analysis of `jax.profiler` traces — measuring α.

`docs/scaling.md`'s efficiency model rests on the exposed-collective
fraction α (the share of collective time NOT hidden under compute).
The reference measured its 90/79 % efficiencies on hardware
(`README.md:27-32` there); this module turns a `bench.py --profile DIR`
capture into a *measured* α so the modeled numbers can be replaced the
moment a chip window opens (VERDICT r3 weak #3).

Works on the Chrome-trace JSON (`*.trace.json.gz`) the profiler writes
next to the xplane protobuf — dependency-free parsing. Device timelines
(pids whose `process_name` names a TPU/accelerator) carry one `X` event
per executed HLO op; async collectives appear as `*-start` / `*-done`
pairs. For every collective we take its WINDOW (start-issue to
done-retire for async pairs; the op's own extent for sync ops),
subtract the union of compute intervals inside it, and call the
remainder exposed:

    alpha = exposed_collective_time / total_collective_window_time

A fully hidden all-reduce (compute covering its whole start→done span)
contributes 0; a synchronous blocking one contributes its full
duration. Union arithmetic makes nested/overlapping trace events safe
to double-count-free.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

# Peak bf16 FLOP/s by device kind (public TPU specs) — the MFU
# denominator, shared by bench.py's analytic estimates and the
# obs-plane `hvd_training_mfu` gauge (obs/profiling.StepProfiler).
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def device_peak_flops(device_kind: Optional[str]) -> Optional[float]:
    """Peak bf16 FLOP/s for a jax ``device_kind`` string; None for
    unknown hardware (CPU, unlisted TPU generations) — MFU is then
    unreported rather than fabricated."""
    if not device_kind:
        return None
    return PEAK_BF16_FLOPS.get(device_kind)


def mfu(flops_per_s: float,
        device_kind: Optional[str]) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over the device peak
    (coarse but honest — docs/mfu.md); None when the peak is
    unknown."""
    peak = device_peak_flops(device_kind)
    if not peak:
        return None
    return round(flops_per_s / peak, 4)


# HLO collective op names (TPU device timeline), e.g. "all-reduce.1",
# "all-reduce-start.7", "all-gather-done.3", "collective-permute.2".
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)"
    r"(-start|-done)?(\.|$|-)", re.IGNORECASE)


def find_trace_file(profile_dir: str,
                    min_mtime: Optional[float] = None) -> Optional[str]:
    """Newest `*.trace.json.gz` under a jax.profiler trace directory.

    `min_mtime` guards against a REUSED profile dir: each capture
    writes a new timestamped subdir and old ones are never cleaned, so
    without the bound a failed serialization would silently hand back a
    previous run's trace as this run's measurement. 2s of slack
    tolerates coarse-mtime filesystems / slight clock skew without
    readmitting day-old captures."""
    paths = [p for p in glob.glob(
        os.path.join(profile_dir, "**", "*.trace.json.gz"),
        recursive=True)
        if min_mtime is None or os.path.getmtime(p) >= min_mtime - 2.0]
    return max(paths, key=os.path.getmtime) if paths else None


def load_trace(profile_dir_or_file: str,
               min_mtime: Optional[float] = None) -> Dict[str, Any]:
    path = profile_dir_or_file
    if os.path.isdir(path):
        found = find_trace_file(path, min_mtime=min_mtime)
        if found is None:
            raise FileNotFoundError(
                f"no *.trace.json.gz under {path!r}"
                + (" (newer than min_mtime)" if min_mtime else ""))
        path = found
    with gzip.open(path, "rt") as f:
        return json.load(f)


def _merge(intervals: List[Tuple[float, float]]):
    """Sorted union of half-open intervals."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _covered(window: Tuple[float, float],
             union: List[Tuple[float, float]]) -> float:
    """Length of `window` covered by the (merged) union."""
    s, e = window
    total = 0.0
    for us, ue in union:
        if ue <= s:
            continue
        if us >= e:
            break
        total += min(e, ue) - max(s, us)
    return total


def _device_pids(events, device_hint: str = ""):
    """pids whose process_name marks a device timeline (TPU /
    accelerator, not host) — the one TPU/host classification heuristic,
    shared by the overlap and breakdown analyses."""
    proc_names: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e["pid"]] = (e.get("args") or {}).get("name", "")

    def is_device(name: str) -> bool:
        if device_hint:
            return device_hint in name
        low = name.lower()
        if "host" in low or "cpu" in low:
            return False
        return any(k in low for k in ("tpu", "device", "accelerator"))

    return {pid for pid, n in proc_names.items() if is_device(n)}


def analyze_overlap(trace: Dict[str, Any],
                    device_hint: str = "") -> Optional[Dict[str, Any]]:
    """Measured α from a loaded Chrome trace.

    Returns None when no device timeline is present (e.g. a CPU-only
    capture — the CPU backend emits host events only). `device_hint`
    optionally narrows which process_name counts as the device (by
    substring); by default anything naming a TPU / device / accelerator
    that is not the host.
    """
    events = (trace if isinstance(trace, list)
              else trace.get("traceEvents", []))
    device_pids = _device_pids(events, device_hint)
    if not device_pids:
        return None

    from collections import defaultdict, deque

    # (label, window) per collective — labels feed the top-exposed
    # report, windows the headline numbers, so both rank by the same
    # start→done extent.
    comm: List[Tuple[str, Tuple[float, float]]] = []
    compute: List[Tuple[float, float]] = []
    # Per-occurrence FIFO pairing: a profiled run repeats each HLO op
    # once per step under the SAME name, so start/done must pair in
    # time order per name — a name-keyed scalar would collapse N steps
    # into the last occurrence and undercount t_comm N-fold.
    start_q: Dict[str, deque] = defaultdict(deque)

    dev_events = sorted(
        (e for e in events
         if e.get("ph") == "X" and e.get("pid") in device_pids
         and e.get("dur") is not None),
        key=lambda e: float(e["ts"]))
    for e in dev_events:
        name = e.get("name", "")
        iv = (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
        m = _COLLECTIVE_RE.match(name)
        if not m:
            compute.append(iv)
            continue
        kind = m.group(2)
        if kind == "-start":
            start_q[name.replace("-start", "-done", 1)].append(
                (name, iv))
        elif kind == "-done":
            q = start_q.get(name)
            _, siv = q.popleft() if q else (None, None)
            # Async window = issue of start → retire of done; a done
            # with no matched start falls back to its own extent.
            comm.append((name, (siv[0] if siv else iv[0], iv[1])))
        else:
            comm.append((name, iv))       # sync collective
    for q in start_q.values():            # starts with no done
        comm.extend(q)
    comm_windows = [w for _, w in comm]
    if not comm_windows:
        return {"alpha": None, "t_comm_us": 0.0, "t_comm_exposed_us": 0.0,
                "t_compute_us": round(sum(e - s for s, e in
                                          _merge(compute)), 3),
                "n_collectives": 0, "device_pids": len(device_pids)}

    compute_union = _merge(compute)
    merged_comm = _merge(comm_windows)
    t_comm = sum(e - s for s, e in merged_comm)
    exposed = sum((e - s) - _covered((s, e), compute_union)
                  for s, e in merged_comm)
    # Per-window attribution for the top offenders, from the SAME
    # paired start→done windows as the headline numbers (un-merged, so
    # overlapping windows may double-count individually).
    per_op = sorted(
        ((name, (w[1] - w[0]) - _covered(w, compute_union))
         for name, w in comm),
        key=lambda kv: -kv[1])

    return {
        "alpha": round(exposed / t_comm, 4) if t_comm else None,
        "t_comm_us": round(t_comm, 3),
        "t_comm_exposed_us": round(exposed, 3),
        "t_compute_us": round(sum(e - s for s, e in compute_union), 3),
        "n_collectives": len(comm_windows),
        "device_pids": len(device_pids),
        "top_exposed": [
            {"name": n, "exposed_us": round(v, 3)}
            for n, v in per_op[:5]],
    }


def analyze_op_breakdown(trace: Dict[str, Any],
                         device_hint: str = "",
                         top_k: int = 10) -> Optional[Dict[str, Any]]:
    """Where the device step time goes, by HLO op category.

    The r4 ResNet diagnosis (BN statistics = 37.8 % of the step,
    docs/mfu.md) was assembled by hand from a trace; this automates it
    so every `bench.py --profile` capture carries its own cost ranking
    in the artifact (VERDICT r4 next-#5: the profiled configs must
    yield named top costs, not just a number).

    Category = the event's `hlo_category` arg when the profiler
    provides it, else the op-name prefix with trailing `.N` indices
    stripped ("fusion.123" → "fusion"). Returns total device-op time,
    per-category shares, and the top individual ops.
    """
    events = (trace if isinstance(trace, list)
              else trace.get("traceEvents", []))
    device_pids = _device_pids(events, device_hint)
    if not device_pids:
        return None

    # A real capture's device pid carries SEVERAL lanes — per-op
    # "XLA Ops" plus aggregate "XLA Modules"/"Steps" rows whose events
    # span whole steps. Summing every lane double-counts and crowns
    # the module event the top "category", so when thread_name
    # metadata identifies an op lane, only those tids count; traces
    # without lane names (synthetic tests) keep all tids.
    thread_names: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = (
                (e.get("args") or {}).get("name", ""))
    op_tids = {k for k, n in thread_names.items()
               if k[0] in device_pids and "xla ops" in n.lower()}

    from collections import defaultdict
    cat_us: Dict[str, float] = defaultdict(float)
    op_us: Dict[str, float] = defaultdict(float)
    total = 0.0
    for e in events:
        if (e.get("ph") != "X" or e.get("pid") not in device_pids
                or e.get("dur") is None):
            continue
        if op_tids and (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        name = e.get("name", "")
        dur = float(e["dur"])
        cat = (e.get("args") or {}).get("hlo_category")
        if not cat:
            cat = re.sub(r"[.\d]+$", "", name) or name
        cat_us[cat] += dur
        op_us[name] += dur
        total += dur
    if total <= 0:
        return None
    cats = sorted(cat_us.items(), key=lambda kv: -kv[1])
    ops = sorted(op_us.items(), key=lambda kv: -kv[1])
    return {
        "t_total_us": round(total, 3),
        "categories": [
            {"category": c, "us": round(v, 3),
             "share": round(v / total, 4)}
            for c, v in cats[:top_k]],
        "top_ops": [
            {"name": n, "us": round(v, 3),
             "share": round(v / total, 4)}
            for n, v in ops[:top_k]],
    }


def analyze_profile_dir(profile_dir: str,
                        min_mtime: Optional[float] = None
                        ) -> Optional[Dict[str, Any]]:
    """Convenience: load the newest trace under `profile_dir` (written
    at or after `min_mtime`, when given) and analyze — overlap α plus
    the per-category op breakdown (`op_breakdown` key); None when there
    is no (fresh enough) trace or no device timeline."""
    try:
        trace = load_trace(profile_dir, min_mtime=min_mtime)
    except (FileNotFoundError, OSError, ValueError):
        return None
    out = analyze_overlap(trace)
    if out is not None:
        out["op_breakdown"] = analyze_op_breakdown(trace)
    return out
