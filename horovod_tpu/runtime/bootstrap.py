"""init()/shutdown() and membership queries.

TPU-native equivalent of the reference's `hvd.init()` call stack
(SURVEY §3.1, `horovod/tensorflow/mpi_ops.cc:1513-1563`): where the
reference spawns a background MPI thread and calls `MPI_Init`, the TPU
build attaches to the JAX runtime — `jax.distributed.initialize` when
launched multi-process (by `hvdrun` or a TPU pod runtime) — and builds a
1-D ``data`` mesh over every participating device. There is no background
thread because under SPMD the collective schedule is decided at compile
time, not negotiated at runtime (SURVEY §7).

Launcher contract (set by ``hvdrun``, horovod_tpu/runner):
  HOROVOD_RANK / HOROVOD_SIZE          process rank / world process count
  HOROVOD_LOCAL_RANK / HOROVOD_LOCAL_SIZE   within-host process placement
  HOROVOD_COORDINATOR                  host:port of the rank-0 coordinator
Standard OMPI/PMI vars are honored as fallbacks so `mpirun`-style launches
also work (parity with `mpi_ops_test.py:31-63`).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from horovod_tpu.runtime import config as _config
from horovod_tpu.runtime import state as _state
from horovod_tpu.runtime.config import config


def _detect_process_env():
    """Read launcher-provided rank/size env vars.

    Returns (process_rank, num_processes, local_rank, local_size,
    coordinator) or None when not launched multi-process.
    """
    env = os.environ
    # The HOROVOD_* pair reads through the registry accessors like
    # every other knob; the OMPI/PMI names are foreign launcher
    # fallbacks outside the registry's HVD_*/HOROVOD_* namespace and
    # stay raw.
    prank_s = _config.env_raw("HOROVOD_RANK")
    psize_s = _config.env_raw("HOROVOD_SIZE")
    if prank_s is None or psize_s is None:
        for rank_var, size_var in (
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
            ("PMI_RANK", "PMI_SIZE"),
        ):
            if rank_var in env and size_var in env:
                prank_s, psize_s = env[rank_var], env[size_var]
                break
        else:
            return None
    prank = int(prank_s)
    psize = int(psize_s)
    lrank = int(_config.env_str(
        "HOROVOD_LOCAL_RANK",
        env.get("OMPI_COMM_WORLD_LOCAL_RANK", str(prank))))
    lsize = int(_config.env_str(
        "HOROVOD_LOCAL_SIZE",
        env.get("OMPI_COMM_WORLD_LOCAL_SIZE", str(psize))))
    coord = _config.env_str("HOROVOD_COORDINATOR")
    return prank, psize, lrank, lsize, coord


def init(devices: Optional[Sequence] = None,
         axis_name: Optional[str] = None) -> int:
    """Initialize horovod_tpu.

    Idempotent, like the reference's atomic-flag-guarded
    `InitializeHorovodOnce` (`mpi_ops.cc:1513-1524`).

    Args:
      devices: optional explicit device list for the mesh (defaults to
        `jax.devices()`).
      axis_name: name of the data-parallel mesh axis (default "data",
        overridable via HOROVOD_MESH_AXIS).

    Returns:
      0 on success (parity with the C `horovod_tensorflow_init`).
    """
    st = _state.global_state()
    with st.lock:
        if st.initialized:
            return 0
        config.refresh()

        import jax

        # hvdrun may force the platform (e.g. cpu workers on a box whose
        # plugin pins JAX_PLATFORMS to the single real TPU); must happen
        # before the backend initializes.
        forced_platform = _config.env_str("HOROVOD_PLATFORM")
        if forced_platform and forced_platform != "auto":
            jax.config.update("jax_platforms", forced_platform)

        proc_env = _detect_process_env()
        if proc_env is not None:
            try:
                already = jax.distributed.is_initialized()
            except AttributeError:  # older jax without is_initialized
                already = False
            prank, psize, lrank, lsize, coord = proc_env
            if psize > 1 and coord and not already:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=psize,
                    process_id=prank,
                )

        devs = list(devices) if devices is not None else list(jax.devices())
        axis = axis_name or config.mesh_axis_name

        from jax.sharding import Mesh
        import numpy as np
        st.mesh = Mesh(np.asarray(devs), (axis,))
        st.axis_name = axis
        st.devices = devs
        st.size = len(devs)

        if proc_env is not None:
            prank, psize, lrank, lsize, _ = proc_env
            st.process_rank = prank
            st.num_processes = psize
            st.local_rank = lrank
            st.local_size = lsize
        else:
            st.process_rank = jax.process_index()
            st.num_processes = jax.process_count()
            st.local_rank = 0
            st.local_size = 1

        # rank == global index of this process's first addressable device:
        # equals the process rank in the launcher's one-device-per-process
        # mode, matching the reference's MPI rank semantics.
        local_set = set(jax.local_devices())
        local_devs = [d for d in devs if d in local_set]
        if local_devs:
            st.rank = devs.index(local_devs[0])
        else:
            st.rank = st.process_rank

        # Native control plane (timeline, stall detection, validation).
        if config.use_native:
            try:
                from horovod_tpu.native import load_native
                st.native = load_native()
                st.native.init(st.rank, st.size, st.local_rank,
                               st.local_size)
            # hvd: disable=HVD006(native build/load can fail a dozen ways — g++ missing, bad toolchain, sandbox; all degrade to pure Python)
            except Exception:
                st.native = None  # graceful pure-Python degradation

        # Multi-controller: connect to the launcher's rendezvous server
        # (the control-message channel replacing MPI TAG_NOTIFY,
        # mpi_ops.cc:225) and synchronize startup.
        kv_addr = _config.env_str("HOROVOD_KV")
        if kv_addr and st.num_processes > 1:
            if st.native is None:
                raise RuntimeError(
                    "multi-process launch requires the native control "
                    "plane (set HOROVOD_NO_NATIVE='' and ensure g++)")
            host, port = kv_addr.rsplit(":", 1)
            if not st.native.connect(host, int(port), timeout_s=60.0):
                raise RuntimeError(
                    f"could not reach rendezvous server at {kv_addr}")
            if not st.native.barrier("hvd_init", 120000):
                raise RuntimeError("init barrier timed out")

        if config.timeline_path:
            from horovod_tpu.utils.timeline import Timeline
            st.timeline = Timeline(config.timeline_path, native=st.native)

        from horovod_tpu.utils.stall import StallMonitor
        st.stall_monitor = StallMonitor(config.stall_warning_time,
                                        native=st.native)

        # Observability exporter (docs/observability.md): env-gated —
        # with HVD_METRICS_PORT unset this is a no-op, so the knob
        # alone turns the HTTP endpoint on for any init()'d process.
        from horovod_tpu.obs.exporter import start_exporter
        start_exporter()

        st.initialized = True
        # Clean teardown even when user scripts never call shutdown()
        # (the reference finalizes from its global destructor,
        # mpi_ops.cc:207-215).
        import atexit
        atexit.register(shutdown)
        return 0


def shutdown() -> None:
    """Graceful shutdown (parity with `mpi_ops.cc:207-215`, SURVEY §5.3)."""
    import sys
    ckpt_mod = sys.modules.get("horovod_tpu.utils.checkpoint")
    if ckpt_mod is not None:
        # Fence any in-flight async checkpoint while the interpreter is
        # still fully alive (atexit is too late for Orbax finalization);
        # swallowing variant — teardown must proceed past a failed save.
        ckpt_mod._fence_swallowing()
    st = _state.global_state()
    with st.lock:
        if not st.initialized:
            return
        if st.timeline is not None:
            st.timeline.close()
        if st.stall_monitor is not None:
            st.stall_monitor.stop()
        if st.native is not None:
            st.native.shutdown()
            st.native = None
        st.reset()
        st.shut_down = True  # observable until the next init()


def is_initialized() -> bool:
    return _state.global_state().initialized


def rank() -> int:
    return _state.check_initialized().rank


def size() -> int:
    return _state.check_initialized().size


def local_rank() -> int:
    return _state.check_initialized().local_rank


def local_size() -> int:
    return _state.check_initialized().local_size


def process_rank() -> int:
    return _state.check_initialized().process_rank


def num_processes() -> int:
    return _state.check_initialized().num_processes


def mesh():
    """The framework-owned `jax.sharding.Mesh` (1-D `data` axis)."""
    return _state.check_initialized().mesh


def connect_kv(addr: Optional[str] = None, *, timeout_s: float = 60.0):
    """Attach this process to the launcher's rendezvous KV plane
    WITHOUT full `init()` — no jax backend, no device mesh, no init
    barrier. Returns the connected native control-plane client.

    This is the multi-controller elastic drill's bootstrap
    (`resilience/drill.py`): worker processes coordinate membership,
    heartbeats and lockstep training entirely through the KV
    (``membership.install_kv(BootstrapKV(connect_kv()))``), so the
    drill runs on any box — including one whose jaxlib lacks
    cross-process CPU collectives. ``addr`` defaults to the
    launcher-set ``HOROVOD_KV``."""
    if addr is None:
        addr = _config.env_str("HOROVOD_KV")
    if not addr or ":" not in addr:
        raise RuntimeError(
            "connect_kv needs a rendezvous address (host:port); "
            "launch under hvdrun or pass addr= explicitly")
    from horovod_tpu.native import load_native
    native = load_native()
    host, port = addr.rsplit(":", 1)
    if not native.connect(host, int(port), timeout_s=timeout_s):
        raise RuntimeError(
            f"could not reach rendezvous server at {addr}")
    return native


def world_generation() -> int:
    """Monotonic elastic-world generation: 0 at launch, +1 per
    committed resize (resilience/membership.py). Readable before
    init() — an uninitialized runtime is generation 0."""
    return _state.global_state().world_generation


def apply_resize(new_rank: int, new_world: int, generation: int, *,
                 rekey_runtime: bool = True) -> None:
    """Re-key the runtime's membership after a committed elastic
    resize (docs/resilience.md "Elastic membership").

    Updates rank/size and the monotonic world generation in place —
    the process survives the resize, so the runtime is re-keyed, not
    re-initialized. Safe on an uninitialized runtime: only the
    bookkeeping fields and the `hvd_elastic_generation` gauge move.
    A real multi-controller deployment additionally rebuilds its mesh
    from the surviving devices before the next compiled step — that
    device-plane re-key is the caller's hook (the mesh cannot be
    rebuilt here for ranks whose devices are gone).

    ``rekey_runtime=False`` records the generation WITHOUT touching
    the membership fields — the in-process simulated worlds
    (`resilience.membership.SimulatedWorld`), where many fake ranks
    share one process, must never rewrite the real runtime's
    rank/size out from under coexisting code."""
    st = _state.global_state()
    with st.lock:
        if generation < st.world_generation:
            raise ValueError(
                f"resize generation {generation} is not monotonic "
                f"(current {st.world_generation})")
        st.world_generation = int(generation)
        if rekey_runtime and st.initialized:
            st.rank = int(new_rank)
            st.size = int(new_world)
            # Compiled collectives are keyed on the old mesh; drop the
            # eager-op cache so nothing re-dispatches against a world
            # that no longer exists.
            st.op_cache = {}
            st.mc_mesh2 = None
    from horovod_tpu.obs import catalog as _obs_catalog
    _obs_catalog.elastic_metrics()["generation"].set(
        float(generation))
