"""Global runtime state.

TPU-native replacement for the reference's `HorovodGlobalState` singleton
(`horovod/tensorflow/mpi_ops.cc:132-219`). The reference state holds a mutex,
tensor table, message queue, fusion buffers, CUDA streams and NCCL comms —
all machinery for ordering collectives across nondeterministically-scheduled
TF executor threads. Under JAX SPMD none of that is needed at runtime: the
collective schedule is fixed at trace time. What remains is membership
(rank/size/local_rank, `mpi_ops.cc:1536-1563` semantics), the device mesh,
and handles to the native control plane (timeline / stall detector /
validation).

Rank model (how Horovod's process-per-accelerator MPMD maps onto JAX):

* A *rank* is a device slot in the 1-D ``data`` mesh, exactly what gradient
  averaging divides by — Horovod's ``size()``.
* Under the ``hvdrun`` launcher each spawned process controls one device
  (CPU mode) or one host's devices (TPU pod), and ``rank()`` equals the
  global index of this process's first device — identical to Horovod's
  process rank in the one-device-per-process case the reference tests
  exercise (`mpi_ops_test.py:31-63`).
* In single-controller mode (one process, N local devices) the controller
  acts on behalf of all N ranks; ``rank()`` is 0 and per-rank identity is
  available inside ``shard_map`` via ``lax.axis_index``.
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class NotInitializedError(ValueError):
    """Raised by rank()/size()/local_rank() before init().

    Mirrors the reference's ValueError('Horovod has not been initialized;
    use horovod.tensorflow.init().') raised on the C API returning -1
    (`horovod/tensorflow/mpi_ops.py:86-124`).
    """


class GlobalState:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.initialized = False
        self.shut_down = False
        # Membership (-1 == uninitialized, mpi_ops.cc:1536-1563 contract).
        self.rank: int = -1
        self.size: int = -1
        self.local_rank: int = -1
        self.local_size: int = -1
        self.process_rank: int = -1
        self.num_processes: int = -1
        # Elastic membership (resilience/membership.py): monotonic
        # world generation — bumps on every committed resize; 0 is the
        # launch world. Survives init-state checks: a resize re-keys
        # the membership fields above in place rather than tearing
        # the runtime down.
        self.world_generation: int = 0
        # Device topology.
        self.mesh: Optional[Any] = None          # jax.sharding.Mesh
        self.axis_name: str = "data"
        self.devices: list = []
        # Native control plane handles (set lazily).
        self.native: Optional[Any] = None        # ctypes library wrapper
        self.timeline: Optional[Any] = None
        self.stall_monitor: Optional[Any] = None
        # Eager-path compile cache: name -> jitted collective.
        self.op_cache: dict = {}
        # (proc, local) mesh for payload-deduplicated mc collectives
        # (built lazily by ops.eager._mc_mesh2).
        self.mc_mesh2: Optional[Any] = None

    def reset(self) -> None:
        self.initialized = False
        self.shut_down = False
        self.rank = self.size = self.local_rank = self.local_size = -1
        self.process_rank = self.num_processes = -1
        self.world_generation = 0
        self.mesh = None
        self.devices = []
        self.op_cache = {}
        self.mc_mesh2 = None
        self.timeline = None
        self.stall_monitor = None


_global_state = GlobalState()


def global_state() -> GlobalState:
    return _global_state


def check_initialized() -> GlobalState:
    """Parity with CheckInitialized (`mpi_ops.cc:1527-1533`)."""
    st = _global_state
    if not st.initialized:
        raise NotInitializedError(
            "horovod_tpu has not been initialized; use horovod_tpu.init().")
    return st
