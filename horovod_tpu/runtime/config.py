"""Environment-variable configuration surface.

Parity with the reference's env-var config system (SURVEY §5.6): the reference
reads `HOROVOD_FUSION_THRESHOLD` (bytes, 0 disables, default 64 MB;
`horovod/tensorflow/mpi_ops.cc:165,1278-1281`) and `HOROVOD_TIMELINE`
(`mpi_ops.cc:1272-1275`), plus a 60 s stall-warning threshold
(`mpi_ops.cc:228`) and 5 ms background tick (`mpi_ops.cc:1292`). The TPU
build keeps the same variable names so existing Horovod deployment recipes
carry over, and adds TPU-specific knobs: `HVD_FUSION_MB` (megabyte alias
of the fusion threshold), `HVD_PREFILL_CHUNK_BUDGET` (serving: prompt
tokens streamed per dispatch step — docs/serving.md "Performance
tuning").
"""

from __future__ import annotations

import dataclasses
import os

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024  # bytes, mpi_ops.cc:165
DEFAULT_STALL_WARNING_TIME = 60.0            # seconds, mpi_ops.cc:228
DEFAULT_CYCLE_TIME_MS = 5.0                  # mpi_ops.cc:1292 (latency floor)
# Serving: max prompt tokens the dispatch loop streams per scheduling
# step (interleaved chunked prefill, docs/serving.md "Performance
# tuning"); <= 0 disables interleaving (whole prompt at once).
DEFAULT_PREFILL_CHUNK_BUDGET = 128


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    try:
        return float(v) if v else default
    except ValueError:
        return default


@dataclasses.dataclass
class Config:
    """Runtime configuration, resolved from the environment at init() time.

    Attributes mirror the reference's knobs; `refresh()` re-reads the
    environment (used by tests and by `hvd.init()`).
    """

    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD
    timeline_path: str = ""
    stall_warning_time: float = DEFAULT_STALL_WARNING_TIME
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    prefill_chunk_budget: int = DEFAULT_PREFILL_CHUNK_BUDGET
    # TPU-specific additions
    allreduce_dtype: str = ""          # e.g. "bfloat16" to reduce in bf16
    mesh_axis_name: str = "data"       # default 1-D data-parallel axis
    use_native: bool = True            # load the C++ control plane
    # "pin" (default): disable XLA's backend AllReduceCombiner in the
    # train-step compile so HOROVOD_FUSION_THRESHOLD's bucket
    # granularity survives to the executed module; "xla": let the
    # backend re-merge (ops/fusion.py combiner_override_options).
    xla_combiner: str = "pin"

    def refresh(self) -> "Config":
        # HOROVOD_FUSION_THRESHOLD (exact bytes, the reference's knob)
        # wins; HVD_FUSION_MB (megabytes, accepts fractions) is the
        # ergonomic alias — "HVD_FUSION_MB=8" == threshold 8 MiB.
        if os.environ.get("HOROVOD_FUSION_THRESHOLD", ""):
            self.fusion_threshold = _env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD)
        elif os.environ.get("HVD_FUSION_MB", ""):
            self.fusion_threshold = int(
                _env_float("HVD_FUSION_MB",
                           DEFAULT_FUSION_THRESHOLD / (1 << 20))
                * (1 << 20))
        else:
            self.fusion_threshold = DEFAULT_FUSION_THRESHOLD
        self.prefill_chunk_budget = _env_int(
            "HVD_PREFILL_CHUNK_BUDGET", DEFAULT_PREFILL_CHUNK_BUDGET)
        self.timeline_path = os.environ.get("HOROVOD_TIMELINE", "")
        self.stall_warning_time = _env_float(
            "HOROVOD_STALL_CHECK_TIME", DEFAULT_STALL_WARNING_TIME)
        self.cycle_time_ms = _env_float(
            "HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS)
        self.allreduce_dtype = os.environ.get("HOROVOD_ALLREDUCE_DTYPE", "")
        self.mesh_axis_name = os.environ.get("HOROVOD_MESH_AXIS", "data")
        self.use_native = os.environ.get("HOROVOD_NO_NATIVE", "") == ""
        self.xla_combiner = os.environ.get("HOROVOD_XLA_COMBINER", "pin")
        return self


config = Config()
config.refresh()
