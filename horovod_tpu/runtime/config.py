"""Environment-variable configuration surface.

Parity with the reference's env-var config system (SURVEY §5.6): the reference
reads `HOROVOD_FUSION_THRESHOLD` (bytes, 0 disables, default 64 MB;
`horovod/tensorflow/mpi_ops.cc:165,1278-1281`) and `HOROVOD_TIMELINE`
(`mpi_ops.cc:1272-1275`), plus a 60 s stall-warning threshold
(`mpi_ops.cc:228`) and 5 ms background tick (`mpi_ops.cc:1292`). The TPU
build keeps the same variable names so existing Horovod deployment recipes
carry over, and adds TPU-specific knobs: `HVD_FUSION_MB` (megabyte alias
of the fusion threshold), `HVD_PREFILL_CHUNK_BUDGET` (serving: prompt
tokens streamed per dispatch step — docs/serving.md "Performance
tuning").

This module is additionally the SINGLE SOURCE OF TRUTH for every
``HVD_*`` / ``HOROVOD_*`` environment knob the codebase reads: each
knob is declared in the `KNOBS` registry below, other modules read the
environment only through the `env_str` / `env_int` / `env_float`
accessors (which refuse unregistered names), and `hvdlint`'s HVD005
rule flags any raw ``os.environ`` read of a knob outside this file.
The registry also generates the environment-knob table in
`docs/troubleshooting.md` (``python -m horovod_tpu.analysis
--write-env-table``), so the docs cannot drift from the code.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024  # bytes, mpi_ops.cc:165
DEFAULT_STALL_WARNING_TIME = 60.0            # seconds, mpi_ops.cc:228
DEFAULT_CYCLE_TIME_MS = 5.0                  # mpi_ops.cc:1292 (latency floor)
# Serving: max prompt tokens the dispatch loop streams per scheduling
# step (interleaved chunked prefill, docs/serving.md "Performance
# tuning"); <= 0 disables interleaving (whole prompt at once).
DEFAULT_PREFILL_CHUNK_BUDGET = 128
# Serving: paged KV cache geometry (docs/serving.md "Paged KV cache").
# Block size in tokens (must divide max_len); block count 0 = auto
# (num_slots x max_len / block_size — byte-parity with the fixed slot
# pool); prefix cache on by default when paging is on.
DEFAULT_KV_BLOCK_SIZE = 16
# Serving fleet (docs/serving.md "Fleet failover"): the ServingRouter's
# defaults — replica count, monitor sweep cadence (failover-detection
# latency floor), cold-replacement budget, the TTFT quantile deriving
# the hedge delay (<= 0 disables hedging), and the retry-budget token
# bucket capacity for shed/failed submits.
DEFAULT_ROUTER_REPLICAS = 2
DEFAULT_ROUTER_POLL_S = 0.02
DEFAULT_ROUTER_REPLACEMENTS = 4
DEFAULT_HEDGE_QUANTILE = 0.95
DEFAULT_RETRY_BUDGET = 16
# Serving decode fast path (docs/serving.md "Decode fast path"):
# speculative-decode proposals per round (the draft-verify depth).
DEFAULT_SPEC_K = 4
# Disaggregated serving (docs/serving.md "Disaggregated serving"):
# prefill/decode pool widths and the KV-block transfer mode.
DEFAULT_DISAGG_PREFILL = 1
DEFAULT_DISAGG_DECODE = 1
DEFAULT_DISAGG_TRANSFER = "host"
# Overload control (docs/serving.md "Overload control"): the
# preemption swap shelf's host-RAM byte budget.
DEFAULT_SWAP_BYTES = 256 << 20


# ---------------------------------------------------------------------------
# The knob registry.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment variable: its type, default, the
    module that consumes it, and a one-line doc (the troubleshooting
    table row)."""

    name: str
    kind: str          # "int" | "float" | "str" | "flag"
    default: str       # rendered default (documentation, not parsing)
    consumer: str      # module that reads it
    doc: str


KNOBS: Dict[str, Knob] = {}


def register_knob(name: str, kind: str, default: str, consumer: str,
                  doc: str) -> Knob:
    """Declare one environment knob. Every ``HVD_*``/``HOROVOD_*``
    variable the codebase reads must be declared here (hvdlint HVD005
    enforces it); re-registration with identical fields is a no-op."""
    knob = Knob(name, kind, default, consumer, doc)
    prev = KNOBS.get(name)
    if prev is not None and prev != knob:
        raise ValueError(
            f"environment knob {name!r} registered twice with "
            f"conflicting declarations:\n  {prev}\n  {knob}")
    KNOBS[name] = knob
    return knob


def _require_registered(name: str):
    if name not in KNOBS:
        raise KeyError(
            f"environment variable {name!r} is not in the "
            f"horovod_tpu.runtime.config knob registry; declare it "
            f"with register_knob() so docs and hvdlint (HVD005) see "
            f"it")


def env_str(name: str, default: str = "") -> str:
    """Read a REGISTERED env knob as a string (raises KeyError for
    undeclared names — the registry is the single source of truth)."""
    _require_registered(name)
    return os.environ.get(name, default)


def env_raw(name: str) -> Optional[str]:
    """Like `env_str` but preserves unset-vs-empty (returns None when
    the variable is absent)."""
    _require_registered(name)
    return os.environ.get(name)


def env_int(name: str, default: int) -> int:
    _require_registered(name)
    v = os.environ.get(name, "")
    try:
        return int(v) if v else default
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    _require_registered(name)
    v = os.environ.get(name, "")
    try:
        return float(v) if v else default
    except ValueError:
        return default


def env_table_md() -> str:
    """The environment-knob table, rendered as GitHub markdown — the
    generated section of docs/troubleshooting.md (tests pin the doc to
    this exact output so the table cannot drift from the registry)."""
    rows = ["| Variable | Type | Default | Read by | Meaning |",
            "| --- | --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(f"| `{k.name}` | {k.kind} | {k.default} | "
                    f"`{k.consumer}` | {k.doc} |")
    return "\n".join(rows) + "\n"


# -- the declarations -------------------------------------------------------
# (kept in one block so the table reads as documentation; consumers
# outside this file fetch values via the env_* accessors above)

register_knob(
    "HOROVOD_FUSION_THRESHOLD", "int", str(DEFAULT_FUSION_THRESHOLD),
    "runtime/config.py",
    "Tensor-fusion bucket size in bytes (0 disables fusion); the "
    "reference's knob, docs/tensor-fusion.md")
register_knob(
    "HVD_FUSION_MB", "float", "64", "runtime/config.py",
    "Megabyte alias of the fusion threshold (accepts fractions); "
    "HOROVOD_FUSION_THRESHOLD wins when both are set")
register_knob(
    "HVD_PREFILL_CHUNK_BUDGET", "int", str(DEFAULT_PREFILL_CHUNK_BUDGET),
    "runtime/config.py",
    "Serving: max prompt tokens streamed per dispatch step "
    "(interleaved chunked prefill; <= 0 streams whole prompts), "
    "docs/serving.md")
register_knob(
    "HVD_KV_BLOCK_SIZE", "int", str(DEFAULT_KV_BLOCK_SIZE),
    "runtime/config.py",
    "Serving: paged-KV block size in tokens (must divide the model's "
    "max_len; ServingEngine(paged=True)), docs/serving.md")
register_knob(
    "HVD_KV_BLOCKS", "int", "0", "runtime/config.py",
    "Serving: paged-KV device block count (0 = auto: num_slots x "
    "max_len / block_size, byte-parity with the fixed slot pool), "
    "docs/serving.md")
register_knob(
    "HVD_PREFIX_CACHE", "int", "1", "runtime/config.py",
    "Serving: shared-prefix caching over the paged KV pool (0 "
    "disables matching/publishing; blocks then free eagerly), "
    "docs/serving.md")
register_knob(
    "HVD_PAGED_KERNEL", "str", "auto", "runtime/config.py",
    "Serving: paged-attention dispatch — 'auto'/'lax' walk only the "
    "FILLED blocks of each lane's table (bitwise-equal to the "
    "legacy gather), 'pallas' adds the fused Pallas decode kernel, "
    "'off' keeps the full-span gather (the fallback oracle), "
    "docs/serving.md 'Decode fast path'")
register_knob(
    "HVD_SPEC_K", "int", str(DEFAULT_SPEC_K), "runtime/config.py",
    "Serving: speculative-decode proposals per round when "
    "ServingEngine(spec_draft=...) doesn't pass spec_k (1..k tokens "
    "retired per tick), docs/serving.md 'Decode fast path'")
register_knob(
    "HVD_WEIGHT_QUANT", "str", "(unset)", "runtime/config.py",
    "Serving: weight-only quantization applied at ServingEngine "
    "construction when weight_quant= isn't passed ('int8' stores "
    "block matmul kernels int8 + per-channel scales), "
    "docs/serving.md 'Decode fast path'")
register_knob(
    "HVD_SERVE_MESH", "str", "(unset)", "runtime/config.py",
    "Serving: shard the engine over a model-parallel mesh when "
    "ServingEngine(mesh=) isn't passed — a device count ('4' = "
    "model=4 over the first 4 devices) or 'axis=N[,axis=N...]' axis "
    "sizes; unset = unsharded, docs/serving.md 'Sharded serving'")
register_knob(
    "HVD_SERVE_MESH_AXIS", "str", "model", "runtime/config.py",
    "Serving: mesh axis name the KV-cache head shards ride (KV heads "
    "partition with their query groups' tensor-parallel shards), "
    "docs/serving.md 'Sharded serving'")
register_knob(
    "HOROVOD_TIMELINE", "str", "(unset)", "runtime/config.py",
    "Write a Chrome-trace timeline to this path, docs/timeline.md")
register_knob(
    "HOROVOD_STALL_CHECK_TIME", "float", str(DEFAULT_STALL_WARNING_TIME),
    "runtime/config.py",
    "Seconds before a pending collective / serving tick warns as "
    "stalled (utils/stall.py)")
register_knob(
    "HOROVOD_CYCLE_TIME", "float", str(DEFAULT_CYCLE_TIME_MS),
    "runtime/config.py",
    "Background dispatch tick in milliseconds (fusion latency floor)")
register_knob(
    "HOROVOD_ALLREDUCE_DTYPE", "str", "(unset)", "runtime/config.py",
    "Reduce gradients in this dtype (e.g. bfloat16) before casting "
    "back")
register_knob(
    "HOROVOD_MESH_AXIS", "str", "data", "runtime/config.py",
    "Name of the default data-parallel mesh axis")
register_knob(
    "HOROVOD_NO_NATIVE", "flag", "(unset)", "runtime/config.py",
    "Non-empty disables the C++ control plane (pure-Python fallback)")
register_knob(
    "HOROVOD_XLA_COMBINER", "str", "pin", "runtime/config.py",
    "'pin' disables XLA's collective combiner so fusion buckets "
    "survive compilation; 'xla' lets the backend re-merge "
    "(ops/fusion.py)")
register_knob(
    "HOROVOD_FLASH_BWD", "str", "pallas", "ops/flash_attention.py",
    "Flash-attention backward kernel override: 'pallas' (fused) or "
    "'recompute' (escape hatch if the fused backward misbehaves)")
register_knob(
    "HVD_IO_RETRIES", "int", "3", "resilience/retry.py",
    "Checkpoint/data I/O retry attempts under the shared RetryPolicy "
    "(0 disables retries)")
register_knob(
    "HVD_CKPT_KEEP", "int", "0", "utils/checkpoint.py",
    "Default step-checkpoint retention for save_step callers that "
    "don't pass keep= (GC prunes oldest beyond N; 0 = keep all), "
    "docs/resilience.md")
register_knob(
    "HVD_CHAOS", "str", "(unset)", "resilience/chaos.py",
    "Arm chaos-injection sites: 'site:count[:p=..][:delay=..],...' "
    "(docs/resilience.md)")
register_knob(
    "HVD_CHAOS_SEED", "int", "0", "resilience/chaos.py",
    "Seed for the deterministic per-site chaos fault schedule")
register_knob(
    "HOROVOD_PLATFORM", "str", "auto", "runtime/bootstrap.py",
    "Force the jax platform before backend init (e.g. 'cpu' workers "
    "on a TPU box); hvdrun sets it for workers")
register_knob(
    "HOROVOD_KV", "str", "(unset)", "runtime/bootstrap.py",
    "host:port of the launcher's rendezvous KV server "
    "(multi-controller bootstrap); set by hvdrun")
register_knob(
    "HOROVOD_RANK", "int", "(launcher)", "runtime/bootstrap.py",
    "Process rank, set by hvdrun (OMPI_COMM_WORLD_RANK / PMI_RANK "
    "are honored as fallbacks)")
register_knob(
    "HOROVOD_SIZE", "int", "(launcher)", "runtime/bootstrap.py",
    "World size, set by hvdrun")
register_knob(
    "HOROVOD_LOCAL_RANK", "int", "(launcher)", "runtime/bootstrap.py",
    "Rank within the host, set by hvdrun")
register_knob(
    "HOROVOD_LOCAL_SIZE", "int", "(launcher)", "runtime/bootstrap.py",
    "Processes on this host, set by hvdrun")
register_knob(
    "HOROVOD_COORDINATOR", "str", "(launcher)", "runtime/bootstrap.py",
    "jax.distributed coordinator address, set by hvdrun")
register_knob(
    "HVD_BENCH_PROBE_BUDGET_S", "float", "(unset)", "bench.py",
    "Caps the benchmark's backend probe loop (seconds) before the "
    "CPU fallback engages")
register_knob(
    "HVD_METRICS_PORT", "int", "(unset)", "obs/exporter.py",
    "Serve Prometheus /metrics + /healthz + /metrics.json on this "
    "port (0 = ephemeral; binds 127.0.0.1 — wider exposure is a "
    "programmatic host= opt-in); honored by hvd.init() and "
    "ServingEngine construction, unset disables the exporter, "
    "docs/observability.md")
register_knob(
    "HVD_EVENTS_LOG", "str", "(unset)", "obs/events.py",
    "Append the structured JSONL event log (restarts, requeues, "
    "sheds, chaos fires, stalls, compiles) to this path "
    "(size-rotated), docs/observability.md")
register_knob(
    "HVD_TRACE_LOG", "str", "(unset)", "obs/spans.py",
    "Mirror every completed causal request span to this JSONL path "
    "(size-rotated); render waterfalls / Chrome traces with "
    "python -m horovod_tpu.obs.spans, docs/observability.md "
    "'Request tracing'")
register_knob(
    "HVD_TRACE_SAMPLE", "float", "1.0", "obs/spans.py",
    "Head-sampling rate for causal span recording (0..1, "
    "deterministic on the trace id so every replica keeps or drops "
    "the SAME traces; 1.0 records everything)")
register_knob(
    "HVD_REQLOG", "str", "(unset)", "obs/reqlog.py",
    "Record every client-entry submit (arrival time, prompt/output "
    "budgets, tenant/priority, prefix-group chain digests) to this "
    "JSONL request log; re-serve it with bench.py --serving "
    "--replay, docs/observability.md 'Record/replay'")
register_knob(
    "HVD_PROFILE_DIR", "str", "(unset)", "obs/profiling.py",
    "Opt-in jax.profiler trace session directory "
    "(obs.profiling.profiler_session); analyze captures with "
    "utils/profile_analysis.py")
register_knob(
    "HVD_EVENTS_RING", "int", "2048", "obs/events.py",
    "In-memory structured-event ring capacity (the /metrics.json "
    "tail window and the flight-recorder bundle's run-up depth), "
    "docs/observability.md")
register_knob(
    "HVD_LOCK_CHECK", "int", "0", "analysis/lockcheck.py",
    "1 = wrap every lockcheck.register()-ed lock in the runtime "
    "order witness (records acquisition edges, flags inversions); "
    "0 = hand back the raw lock, zero overhead (docs/analysis.md)")
register_knob(
    "HVD_LOCK_CHECK_OUT", "str", "(unset)", "analysis/lockcheck.py",
    "With HVD_LOCK_CHECK=1: write the observed lock-order graph and "
    "any inversions as JSON to this path at process exit (the CI "
    "zero-inversion gate's evidence)")
register_knob(
    "HVD_FLIGHT_DIR", "str", "(unset)", "obs/flightrec.py",
    "Crash flight recorder: dump a post-mortem bundle (event ring + "
    "metric snapshot + in-flight trace_ids + config) here on watchdog "
    "restarts, chaos fires, stall trips, NaN rollbacks and dispatch "
    "crashes; unset disables, docs/observability.md")
register_knob(
    "HVD_FLIGHT_KEEP", "int", "8", "obs/flightrec.py",
    "Flight-recorder retention: newest N bundles kept, oldest pruned "
    "(0 = keep all)")
register_knob(
    "HVD_SLO", "str", "(unset)", "obs/slo.py",
    "SLO objectives as burn-rate spec, e.g. 'ttft=0.5,tpot=0.1,"
    "shed=0.02,target=0.99,fast=60,slow=600'; a fast-burn breach "
    "flips /healthz to 503, docs/observability.md")
register_knob(
    "HVD_FLEET_RANKS", "str", "(unset)", "obs/aggregate.py",
    "Comma-separated per-rank exporter base URLs (host:port) the "
    "/fleet endpoint aggregates; unset = this process's registry "
    "alone, docs/observability.md")
register_knob(
    "HVD_STRAGGLER_CYCLES", "int", "64", "obs/straggler.py",
    "Collective dispatches per straggler timing-window exchange "
    "(0 disables the periodic exchange; windows still accumulate "
    "for the fleet collector)")
register_knob(
    "HVD_ROUTER_REPLICAS", "int", str(DEFAULT_ROUTER_REPLICAS),
    "runtime/config.py",
    "Serving fleet: ServingRouter replica count when the caller "
    "doesn't pass num_replicas (bench --router / examples), "
    "docs/serving.md 'Fleet failover'")
register_knob(
    "HVD_ROUTER_POLL", "float", str(DEFAULT_ROUTER_POLL_S),
    "runtime/config.py",
    "Serving fleet: router monitor sweep interval in seconds "
    "(health checks, hedge scans, migration processing, chaos "
    "kills) — the failover-detection latency floor")
register_knob(
    "HVD_ROUTER_REPLACEMENTS", "int", str(DEFAULT_ROUTER_REPLACEMENTS),
    "runtime/config.py",
    "Serving fleet: cold replacements the router may build for "
    "dead/drained replicas over its lifetime (the factory-call "
    "budget; the fleet shrinks once spent)")
register_knob(
    "HVD_HEDGE_QUANTILE", "float", str(DEFAULT_HEDGE_QUANTILE),
    "runtime/config.py",
    "Serving fleet: TTFT quantile (0, 1] deriving the hedge delay — "
    "a request with no first token after the fleet's q-th TTFT "
    "quantile is duplicated on a second replica and the loser "
    "cancelled; <= 0 disables hedging")
register_knob(
    "HVD_LEASE_S", "float", "2.0", "resilience/membership.py",
    "Elastic membership: heartbeat lease in seconds — a rank whose "
    "newest heartbeat is older than this is declared dead and the "
    "world resizes (docs/resilience.md 'Elastic membership')")
register_knob(
    "HVD_HEARTBEAT_S", "float", "(lease/4)",
    "resilience/membership.py",
    "Elastic membership: heartbeat write cadence in seconds "
    "(default lease/4 — the lease tolerates isolated dropped beats)")
register_knob(
    "HVD_PREEMPT_GRACE_S", "float", "30", "resilience/elastic.py",
    "Preemption grace window in seconds: how long after a preemption "
    "notice (SIGUSR1/SIGTERM) the host is expected to survive — "
    "PreemptionHandler.grace_remaining() budgets the emergency "
    "checkpoint against it (docs/resilience.md)")
register_knob(
    "HVD_DETECTOR_SWEEP_S", "float", "0.05",
    "resilience/detector.py",
    "Failure detector: shared sweep-thread cadence in seconds (per-"
    "peer poll intervals may ask for faster; floor 0.005), "
    "docs/resilience.md 'Failure detection'")
register_knob(
    "HVD_DETECTOR_HYSTERESIS", "int", "2",
    "resilience/detector.py",
    "Failure detector: consecutive good observations required to "
    "leave SUSPECT (recovery hysteresis; death is never gated)")
register_knob(
    "HVD_DETECTOR_FLAP_WINDOW_S", "float", "30",
    "resilience/detector.py",
    "Failure detector: flap-damping window — recoveries inside it "
    "count against HVD_DETECTOR_FLAP_MAX")
register_knob(
    "HVD_DETECTOR_FLAP_MAX", "int", "4",
    "resilience/detector.py",
    "Failure detector: recoveries allowed per flap window before the "
    "peer is damped (held at SUSPECT — drained, not resurrected — "
    "until the window decays)")
register_knob(
    "HVD_ELASTIC_DRILL_TIMEOUT_S", "float", "300",
    "resilience/drill.py",
    "Multi-process elastic drill: wall-clock budget for the whole "
    "hvdrun-launched worker world (driver kills the job past it)")
register_knob(
    "HVD_RETRY_BUDGET", "int", str(DEFAULT_RETRY_BUDGET),
    "runtime/config.py",
    "Serving fleet: router retry-budget token-bucket capacity for "
    "shed/failed submits (refills at capacity/60 per second; 0 "
    "disables retries — first answer wins)")
register_knob(
    "HVD_DISAGG", "flag", "0",
    "serving/disagg.py",
    "Disaggregated serving: 1 makes ServingRouter construct a "
    "DisaggRouter — requests prefill on a dedicated pool, migrate "
    "their KV blocks to a decode pool at prefill-complete "
    "(docs/serving.md \"Disaggregated serving\")")
register_knob(
    "HVD_DISAGG_PREFILL", "int", str(DEFAULT_DISAGG_PREFILL),
    "serving/disagg.py",
    "Disaggregated serving: prefill-pool replica count (sized "
    "independently of the decode pool — the MPMD split's point)")
register_knob(
    "HVD_DISAGG_DECODE", "int", str(DEFAULT_DISAGG_DECODE),
    "serving/disagg.py",
    "Disaggregated serving: decode-pool replica count (the base "
    "router fleet; HVD_ROUTER_REPLICAS is ignored when disagg is "
    "on)")
register_knob(
    "HVD_DISAGG_TRANSFER", "str", DEFAULT_DISAGG_TRANSFER,
    "serving/transfer.py",
    "KV-block transfer mode between pools: 'host' bounces rows "
    "through host memory (any layout pair), 'device' keeps them "
    "device-resident and device_puts into the destination layout")
register_knob(
    "HVD_PREEMPT", "flag", "0",
    "serving/engine.py",
    "Overload control: 1 lets a blocked higher-priority request "
    "preempt strictly lower-priority decode streams token-exactly "
    "(swap or recompute), and switches paged admission to optimistic "
    "watermark reservations (docs/serving.md \"Overload control\")")
register_knob(
    "HVD_SWAP_BYTES", "int", str(DEFAULT_SWAP_BYTES),
    "serving/overload.py",
    "Overload control: host-RAM byte budget for the preemption swap "
    "shelf (preempted streams' KV blocks awaiting resume); 0 "
    "degrades every preemption to recompute")
register_knob(
    "HVD_TENANT_WEIGHTS", "str", "",
    "serving/admission.py",
    "Overload control: per-tenant WFQ weights, "
    "'name=<w>,name=<w>,...' — admission serves tenant lanes in "
    "weight proportion and caps each named tenant's queue share at "
    "weight/total; empty = every tenant weighs 1, no caps")
register_knob(
    "HVD_BROWNOUT", "flag", "1",
    "serving/overload.py",
    "Overload control: per-tenant graduated degradation ladder "
    "(1 no hedging -> 2 spec-k capped -> 3 lowest-priority streams "
    "preempted), driven by per-tenant SLO fast burn and the "
    "serving.overload_storm chaos site; 0 disables")


# ---------------------------------------------------------------------------
# The resolved runtime config.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Config:
    """Runtime configuration, resolved from the environment at init() time.

    Attributes mirror the reference's knobs; `refresh()` re-reads the
    environment (used by tests and by `hvd.init()`).
    """

    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD
    timeline_path: str = ""
    stall_warning_time: float = DEFAULT_STALL_WARNING_TIME
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    prefill_chunk_budget: int = DEFAULT_PREFILL_CHUNK_BUDGET
    # Paged KV cache (serving): block size in tokens, device block
    # count (0 = auto byte-parity with the fixed pool), and the
    # shared-prefix cache switch.
    kv_block_size: int = DEFAULT_KV_BLOCK_SIZE
    kv_blocks: int = 0
    prefix_cache: bool = True
    # Decode fast path (docs/serving.md): paged-attention dispatch
    # mode, draft-verify depth, and the construction-time weight
    # quantization default ("" = off).
    paged_kernel: str = "auto"
    spec_k: int = DEFAULT_SPEC_K
    weight_quant: str = ""
    # Sharded serving (docs/serving.md "Sharded serving"): the default
    # engine mesh ("" = unsharded) and the axis the KV head shards
    # ride.
    serve_mesh: str = ""
    serve_mesh_axis: str = "model"
    # Serving fleet (ServingRouter, docs/serving.md "Fleet failover").
    router_replicas: int = DEFAULT_ROUTER_REPLICAS
    router_poll_s: float = DEFAULT_ROUTER_POLL_S
    router_replacements: int = DEFAULT_ROUTER_REPLACEMENTS
    hedge_quantile: float = DEFAULT_HEDGE_QUANTILE
    retry_budget: int = DEFAULT_RETRY_BUDGET
    # Disaggregated serving (docs/serving.md "Disaggregated
    # serving"): the DisaggRouter switch, the independent pool
    # widths, and the KV-block transfer mode.
    disagg: int = 0
    disagg_prefill: int = DEFAULT_DISAGG_PREFILL
    disagg_decode: int = DEFAULT_DISAGG_DECODE
    disagg_transfer: str = DEFAULT_DISAGG_TRANSFER
    # Overload control plane (docs/serving.md "Overload control"):
    # token-exact preemption switch, swap-shelf byte budget,
    # per-tenant WFQ weights, and the brownout ladder switch.
    preempt: bool = False
    swap_bytes: int = DEFAULT_SWAP_BYTES
    tenant_weights: str = ""
    brownout: bool = True
    # TPU-specific additions
    allreduce_dtype: str = ""          # e.g. "bfloat16" to reduce in bf16
    mesh_axis_name: str = "data"       # default 1-D data-parallel axis
    use_native: bool = True            # load the C++ control plane
    # "pin" (default): disable XLA's backend AllReduceCombiner in the
    # train-step compile so HOROVOD_FUSION_THRESHOLD's bucket
    # granularity survives to the executed module; "xla": let the
    # backend re-merge (ops/fusion.py combiner_override_options).
    xla_combiner: str = "pin"

    def refresh(self) -> "Config":
        # HOROVOD_FUSION_THRESHOLD (exact bytes, the reference's knob)
        # wins; HVD_FUSION_MB (megabytes, accepts fractions) is the
        # ergonomic alias — "HVD_FUSION_MB=8" == threshold 8 MiB.
        if env_str("HOROVOD_FUSION_THRESHOLD"):
            self.fusion_threshold = _env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD)
        elif env_str("HVD_FUSION_MB"):
            self.fusion_threshold = int(
                _env_float("HVD_FUSION_MB",
                           DEFAULT_FUSION_THRESHOLD / (1 << 20))
                * (1 << 20))
        else:
            self.fusion_threshold = DEFAULT_FUSION_THRESHOLD
        self.prefill_chunk_budget = _env_int(
            "HVD_PREFILL_CHUNK_BUDGET", DEFAULT_PREFILL_CHUNK_BUDGET)
        self.kv_block_size = _env_int("HVD_KV_BLOCK_SIZE",
                                      DEFAULT_KV_BLOCK_SIZE)
        self.kv_blocks = _env_int("HVD_KV_BLOCKS", 0)
        self.prefix_cache = _env_int("HVD_PREFIX_CACHE", 1) != 0
        self.paged_kernel = env_str("HVD_PAGED_KERNEL", "auto")
        self.spec_k = _env_int("HVD_SPEC_K", DEFAULT_SPEC_K)
        self.weight_quant = env_str("HVD_WEIGHT_QUANT")
        self.serve_mesh = env_str("HVD_SERVE_MESH")
        self.serve_mesh_axis = env_str("HVD_SERVE_MESH_AXIS", "model")
        self.router_replicas = _env_int("HVD_ROUTER_REPLICAS",
                                        DEFAULT_ROUTER_REPLICAS)
        self.router_poll_s = _env_float("HVD_ROUTER_POLL",
                                        DEFAULT_ROUTER_POLL_S)
        self.router_replacements = _env_int(
            "HVD_ROUTER_REPLACEMENTS", DEFAULT_ROUTER_REPLACEMENTS)
        self.hedge_quantile = _env_float("HVD_HEDGE_QUANTILE",
                                         DEFAULT_HEDGE_QUANTILE)
        self.retry_budget = _env_int("HVD_RETRY_BUDGET",
                                     DEFAULT_RETRY_BUDGET)
        self.disagg = _env_int("HVD_DISAGG", 0)
        self.disagg_prefill = _env_int("HVD_DISAGG_PREFILL",
                                       DEFAULT_DISAGG_PREFILL)
        self.disagg_decode = _env_int("HVD_DISAGG_DECODE",
                                      DEFAULT_DISAGG_DECODE)
        self.disagg_transfer = env_str("HVD_DISAGG_TRANSFER",
                                       DEFAULT_DISAGG_TRANSFER)
        self.preempt = _env_int("HVD_PREEMPT", 0) != 0
        self.swap_bytes = _env_int("HVD_SWAP_BYTES",
                                   DEFAULT_SWAP_BYTES)
        self.tenant_weights = env_str("HVD_TENANT_WEIGHTS")
        self.brownout = _env_int("HVD_BROWNOUT", 1) != 0
        self.timeline_path = env_str("HOROVOD_TIMELINE")
        self.stall_warning_time = _env_float(
            "HOROVOD_STALL_CHECK_TIME", DEFAULT_STALL_WARNING_TIME)
        self.cycle_time_ms = _env_float(
            "HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS)
        self.allreduce_dtype = env_str("HOROVOD_ALLREDUCE_DTYPE")
        self.mesh_axis_name = env_str("HOROVOD_MESH_AXIS", "data")
        self.use_native = env_str("HOROVOD_NO_NATIVE") == ""
        self.xla_combiner = env_str("HOROVOD_XLA_COMBINER", "pin")
        return self


# Backwards-compatible aliases (pre-registry internal helpers).
_env_int = env_int
_env_float = env_float

config = Config()
config.refresh()
