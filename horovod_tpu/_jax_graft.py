"""Version graft: backfill newer-jax API names this codebase targets
onto older installed jax (observed floor: 0.4.37).

The framework (and its tests) are written against the modern surface —
`jax.shard_map(..., check_vma=)`, `jax.sharding.get_abstract_mesh`,
`jax.set_mesh` — while deployment images can pin older jax. Ambient-
mesh and axis-type lookups are insulated in `parallel.mesh`
(`abstract_mesh` / `auto_axis_names` / `use`); what cannot be wrapped
at one site is `jax.shard_map` itself, which call sites (including
tests) invoke as a jax attribute. On old jax that name lives at
`jax.experimental.shard_map.shard_map` with `check_rep=` instead of
`check_vma=`; `install()` grafts a translating alias onto the jax
module when — and only when — the real attribute is absent, so on
modern jax this module is a no-op and nothing shadows the native API.

Imported for its side effect from `horovod_tpu/__init__` (before any
framework module traces a shard_map).
"""

from __future__ import annotations

import functools

import jax


def install():
    if hasattr(jax, "shard_map"):
        return

    # Modern jax defaults jax_threefry_partitionable=True; this
    # codebase's sharded-RNG contracts (e.g. sharded-at-birth init ==
    # default init, `init_lm_state(sharded_init=True)`) are written
    # against that default. Old jax ships False — align it.
    try:
        if not jax.config.jax_threefry_partitionable:
            jax.config.update("jax_threefry_partitionable", True)
    except AttributeError:  # pragma: no cover — option removed
        pass

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None, **kw):
        # `axis_names` (modern: restrict which mesh axes turn Manual)
        # has no old-API equivalent; the old behavior equals the
        # modern default (all axes), so only the default is accepted.
        if axis_names is not None:
            raise NotImplementedError(
                "shard_map(axis_names=...) needs jax >= 0.6; this "
                "environment runs the jax.experimental graft")
        # check_vma maps onto the old checker's check_rep, but the
        # bodies in this codebase state their replication facts in the
        # NEW vocabulary (`jax.typeof(x).vma` ShapeDtypeStructs, e.g.
        # the Pallas flash kernel under ring/Ulysses SP) which the old
        # checker cannot read — its True mode rejects valid programs
        # ("No replication rule for pallas_call"). The check is a
        # static lint with no runtime semantics, so the graft always
        # disables it.
        del check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, **kw)

    jax.shard_map = shard_map


install()
