"""Slot-pool KV cache: the device state behind continuous batching.

The linear decode cache (`parallel.tensor.ParallelSelfAttention`,
``decode=True``) keeps ONE scalar ``cache_index`` shared by the whole
batch — every row must sit at the same fill level, which is exactly
what continuous batching breaks (each slot holds a different request
at a different depth). `models.transformer`'s slot primitives
generalize that cache to a pool: every leaf gains a leading
[num_slots] axis (the per-layer fill scalars become per-slot vectors),
prefill streams a prompt into ONE slot through the `chunked_prefill`
cache-wide-mask path, and the decode tick vmaps the B=1 decode step
over the slot axis. This module wraps those primitives with the
host-side bookkeeping the scheduler needs: a free list, per-slot
sampling state (temperature / top_p / RNG stream), per-slot live/done
occupancy flags, and reset-on-retire hygiene.

Slot lifecycle::

    FREE --alloc()--> begin_prefill() [reset]
      ^                 --prefill_chunk()*--> finish_prefill()
      |                                           |  (live flag set)
      +------------------- free() <--- ACTIVE --tick_dispatch()*

Hot-path pipelining (the PR-3 rebuild): the decode tick is split into
`tick_dispatch()` (enqueue the vmapped tick + start an async
device->host copy of the token buffer) and `tick_sync(handle)` (the
blocking read). The scheduler dispatches tick N+1 BEFORE syncing tick
N, so the host-side bookkeeping and the transfer hide behind the
device's compute — one exposed host sync per token becomes ~one per
request. Occupancy is device state too: a ``live`` mask freezes the
fill index of FREE and mid-prefill lanes (no idle creep, no corruption
of a half-streamed prompt), and a ``done`` flag implements on-device
stop detection — a lane that emitted eos keeps emitting eos, so the
host can retire a pipeline-depth late purely from the async token
buffer.
"""

from __future__ import annotations

import contextlib
import time
import warnings
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from horovod_tpu.annotations import hot_path
from horovod_tpu.models.transformer import (
    TransformerLM, init_slot_cache, prefill_chunks, sample_token,
    shard_slot_cache, slot_decode_model, slot_decode_tick,
    slot_prefill_advance, slot_prefill_chunk, slot_reset,
    slot_spec_round,
)
from horovod_tpu.parallel.mesh import replicate, use


def validate_spec_draft(model: TransformerLM, spec_draft,
                        spec_k: int):
    """Shared spec-decode construction checks (both pools and the
    engine): the draft must share the target's vocab, neither model
    may roll a sliding-window cache (rewind would overwrite live
    slots — `models.speculative`'s constraint), the draft cache must
    cover every position the target can reach, and k must leave room
    for at least one proposal."""
    draft_model, _ = spec_draft
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if draft_model.vocab_size != model.vocab_size:
        raise ValueError(
            f"spec draft vocab ({draft_model.vocab_size}) != target "
            f"vocab ({model.vocab_size})")
    if model.window is not None or draft_model.window is not None:
        raise ValueError(
            "speculative decoding cannot rewind a sliding-window "
            "(rolling) cache; use window=None models")
    if draft_model.max_len < model.max_len:
        raise ValueError(
            f"spec draft max_len ({draft_model.max_len}) must cover "
            f"the target's ({model.max_len})")


@jax.jit
def _first_token(logits, temp, top_p, key, skips):
    """First-token sample closing the prefill: split the request key
    exactly as `generate` does (``rng, r0 = split(key)``; the tick
    keeps splitting ``rng``), so a request's sample stream is
    reproducible from its seed regardless of which slot it lands in or
    what else shares the batch.

    ``skips`` (traced int32, normally 0) advances the key by that many
    carry-splits FIRST — the forced-prefix continuation hook
    (docs/serving.md "Fleet failover"): a request resubmitted with its
    first k generated tokens folded into the prompt must sample token
    k+1 from the SAME r_k the original stream would have used, since
    the per-request stream is keyed by token ordinal (each token
    consumes one ``rng, r = split(rng)``), not by position. A traced
    bound keeps this one compiled program for every k."""
    key = jax.lax.fori_loop(
        0, skips, lambda i, k: jax.random.split(k)[0], key)
    rng, r0 = jax.random.split(key)
    tok = sample_token(logits, temp, top_p, r0)
    return tok.astype(jnp.int32), rng


def __getattr__(name):
    """Deprecation shim for the PR-1/2 idle-reset machinery. The PR-3
    tick freezes non-live lanes' fill indices ON DEVICE
    (`slot_decode_tick`'s ``live`` mask), so idle creep is exactly 0,
    no periodic reset runs, and the old ceiling constant is
    meaningless — importers get the historical value plus a warning
    until they migrate."""
    if name == "RESET_IDLE_TICKS":
        warnings.warn(
            "RESET_IDLE_TICKS is obsolete: idle lanes' fill indices "
            "are frozen on device since the PR-3 tick (live mask) — "
            "idle creep is 0 and no periodic reset exists to bound",
            DeprecationWarning, stacklevel=2)
        return 64
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


@dataclass(frozen=True)
class Admission:
    """One granted admission: the decode lane, how many prompt tokens
    the KV cache already holds (``skipped`` — prefill starts there; 0
    outside the paged pool's prefix cache), and the block-level hit
    accounting behind it (`serving.paging`)."""

    slot: int
    skipped: int = 0          # prompt tokens covered by matched blocks
    matched_blocks: int = 0   # prefix blocks pinned from the cache
    queried_blocks: int = 0   # block-aligned prefix blocks looked up


class TickHandle:
    """One in-flight decode tick: the device token buffer (its host
    copy already started via `copy_to_host_async`). `tick_sync` turns
    it into the [num_slots] numpy vector."""

    __slots__ = ("toks",)

    def __init__(self, toks):
        self.toks = toks


class SlotPool:
    """A fixed pool of ``num_slots`` decode slots over one shared
    slot-pool KV cache.

    All device work (prefill chunks, the vmapped tick, slot resets)
    happens on the caller's thread — the engine's dispatch thread —
    so jax never sees concurrent mutation of the pool state.

    ``eos_id`` arms on-device stop detection (None = disabled): the
    tick itself masks lanes that have emitted eos, so a finished slot
    can never leak a post-eos token to the host even when retirement
    lags a pipelined tick behind.
    """

    def __init__(self, model: TransformerLM, params, num_slots: int,
                 *, mesh=None, eos_id: Optional[int] = None,
                 spec_draft=None, spec_k: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.model = model
        self.dec_model = slot_decode_model(model)
        self.params = params
        self.num_slots = num_slots
        self.mesh = mesh
        self.eos_id = eos_id
        self._eos = jnp.int32(-1 if eos_id is None else eos_id)
        self._cache = init_slot_cache(model, num_slots)
        # Speculative decoding (docs/serving.md "Decode fast path"):
        # ``spec_draft`` = (draft_model, draft_params) arms the
        # draft-verify round — the tick is then `spec_round`, retiring
        # 1..k+1 tokens per lane per round, greedy-only. The draft
        # rides its own linear slot cache, prefilled chunk-for-chunk
        # alongside the target's.
        self.spec_draft = spec_draft
        self.spec_k = int(spec_k) if spec_draft is not None else 0
        self.drf_model = self.drf_params = self._drf_cache = None
        if self.spec_on:
            validate_spec_draft(model, spec_draft, self.spec_k)
            draft_model, draft_params = spec_draft
            self.drf_model = slot_decode_model(draft_model)
            self.drf_params = draft_params
            self._drf_cache = init_slot_cache(draft_model, num_slots)
        self._toks = jnp.zeros((num_slots,), jnp.int32)
        self._temps = jnp.zeros((num_slots,), jnp.float32)
        self._top_ps = jnp.ones((num_slots,), jnp.float32)
        self._rngs = jnp.stack(
            [jax.random.PRNGKey(i) for i in range(num_slots)])
        # Device occupancy: live gates fill-index advance (FREE and
        # mid-prefill lanes frozen), done is the on-device stop flag.
        self._live = jnp.zeros((num_slots,), bool)
        self._done = jnp.zeros((num_slots,), bool)
        self._free: List[int] = list(range(num_slots))
        # Sharded serving (docs/serving.md "Sharded serving"): commit
        # the KV cache sharded along the heads axis and replicate the
        # per-lane decision vectors across the mesh, so every jitted
        # slot primitive runs GSPMD-partitioned under `use(mesh)` —
        # the PROGRAM is unchanged; the sharding enters through the
        # committed operand layouts. One host decision (slot ids,
        # sampling state) drives all shards.
        if mesh is not None:
            self._cache = shard_slot_cache(self._cache, mesh)
            if self._drf_cache is not None:
                self._drf_cache = shard_slot_cache(self._drf_cache,
                                                   mesh)
            (self._toks, self._temps, self._top_ps, self._rngs,
             self._live, self._done, self._eos) = replicate(
                mesh, (self._toks, self._temps, self._top_ps,
                       self._rngs, self._live, self._done, self._eos))
        # Compile awareness for the engine watchdog: True while a
        # device call whose shape this pool has not executed before is
        # in flight — a first-time XLA compile can take arbitrarily
        # long and must not read as a stuck tick (stuck detection is
        # suppressed while set). Shapes already seen are jit-cache
        # hits, so the flag clears in microseconds for warm calls.
        self.maybe_compiling = False
        self._seen_shapes: set = set()
        # First-time-shape count for this pool (warmup + hot path);
        # the engine subtracts its post-warmup baseline to report
        # hot-path compiles (the "no compile in the timed window"
        # guarantee ci.sh asserts).
        self.compiles = 0
        # Brownout rung >= 2 (docs/serving.md "Overload control"):
        # caps the speculative k mid-stream — greedy spec decode is
        # bitwise for ANY k, so the cap sheds draft compute without
        # touching token streams (one extra compile per new k).
        self.spec_cap = None

    @property
    def spec_on(self) -> bool:
        return self.spec_draft is not None and self.spec_k > 0

    def _ctx(self):
        return use(self.mesh) if self.mesh is not None \
            else contextlib.nullcontext()

    def _note_shape(self, key):
        if key not in self._seen_shapes:
            self.compiles += 1
            self._seen_shapes.add(key)
            # Observability: compiles are discrete operator-visible
            # events (a compile inside a warmed serving window is a
            # bug ci.sh asserts against) — count them process-wide
            # and log which program shape triggered.
            from horovod_tpu.obs import catalog as _obs_catalog
            from horovod_tpu.obs import events as _events
            _obs_catalog.serving_metrics()["compiles"].inc()
            _events.emit("serving.compile", shape=repr(key))

    def clone_fresh(self) -> "SlotPool":
        """A brand-new pool over the same model/params/mesh — the
        engine watchdog's restart primitive (docs/resilience.md). The
        old pool may be mid-tick in a hung dispatch thread, so its
        cache and free-list are untrusted; a clone starts from zeroed
        slots. Compiled tick/prefill programs are keyed by the model
        config and shapes, both unchanged, so the clone recompiles
        nothing."""
        fresh = SlotPool(self.model, self.params, self.num_slots,
                         mesh=self.mesh, eos_id=self.eos_id,
                         spec_draft=self.spec_draft,
                         spec_k=self.spec_k)
        # The jit cache is process-global: shapes this pool compiled
        # are warm for the clone too (and the compile count carries,
        # so hot-path-compile accounting survives a restart).
        fresh._seen_shapes = set(self._seen_shapes)
        fresh.spec_cap = self.spec_cap
        fresh.compiles = self.compiles
        return fresh

    def fill_indices(self) -> np.ndarray:
        """Per-slot cache fill index, maxed across layers (and the
        pos_index at learned-position models) — introspection for
        tests and debugging (e.g. asserting idle lanes stay at 0)."""
        from jax.tree_util import tree_flatten_with_path
        flat, _ = tree_flatten_with_path(self._cache)
        idx = [np.asarray(leaf) for path, leaf in flat
               if "index" in str(path)]
        assert idx, "slot cache has no index leaves"
        return np.max(np.stack(idx), axis=0)

    # -- occupancy ----------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def busy_slots(self) -> int:
        return self.num_slots - len(self._free)

    def has_free(self) -> bool:
        return bool(self._free)

    # -- lifecycle ----------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Claim a free slot; None when the pool is full. The slot's
        device rows are NOT assumed clean — `begin_prefill` re-zeroes
        them at use time."""
        if not self._free:
            return None
        return self._free.pop()

    def can_admit(self, prompt, max_new: int) -> bool:
        """Scheduler admission gate (shared protocol with the paged
        pool): the fixed pool's only capacity axis is free slots —
        every slot already reserves max_len KV rows, so prompt/budget
        never constrain further."""
        del prompt, max_new
        return self.has_free()

    def admit(self, prompt, max_new: int) -> Optional[Admission]:
        """Claim a slot for one request (shared protocol with
        `serving.paging.PagedSlotPool`, where this is also where
        blocks are reserved and the prompt's prefix is matched). The
        fixed pool never skips prefix tokens."""
        del prompt, max_new
        slot = self.alloc()
        return None if slot is None else Admission(slot=slot)

    def begin_prefill(self, slot: int):
        """Zero ``slot``'s rows and clear its live/done flags — the
        mandatory preamble before streaming a prompt in. The reset
        makes admission self-contained (a slot is correct to prefill
        whatever its history: clone restarts, crashed predecessors,
        direct pool use)."""
        self.maybe_compiling = ("reset",) not in self._seen_shapes
        try:
            with self._ctx():
                self._cache = slot_reset(self.dec_model, self._cache,
                                         jnp.int32(slot))
                if self.spec_on:
                    self._drf_cache = slot_reset(
                        self.drf_model, self._drf_cache,
                        jnp.int32(slot))
                self._live = self._live.at[slot].set(False)
                self._done = self._done.at[slot].set(False)
            self._note_shape(("reset",))
        finally:
            self.maybe_compiling = False

    def prefill_chunk(self, slot: int, chunk):
        """Append one prompt chunk (1-D int tokens, power-of-two
        length from `prefill_chunks`) into ``slot``'s cache; returns
        the chunk's last-position logits (a DEVICE array — no host
        sync). The slot stays non-live, so interleaved decode ticks
        freeze its fill index and the next chunk lands exactly where
        this one stopped."""
        # hvd: disable=HVD001(chunk is host-side prompt tokens from the admission queue, never a device array — no sync)
        chunk = np.asarray(chunk)
        c = int(chunk.shape[0])
        self.maybe_compiling = ("prefill", c) not in self._seen_shapes
        try:
            with self._ctx():
                self._cache, logits = slot_prefill_chunk(
                    self.dec_model, self.params, self._cache,
                    jnp.int32(slot), jnp.asarray(chunk, jnp.int32))
                if self.spec_on:
                    # The draft's cache must hold the SAME prompt as
                    # the target's before any round — same chunk
                    # schedule, advance-only (no logits: the first
                    # token is always the target's).
                    self._drf_cache = slot_prefill_advance(
                        self.drf_model, self.drf_params,
                        self._drf_cache, jnp.int32(slot),
                        jnp.asarray(chunk, jnp.int32))
            self._note_shape(("prefill", c))
            return logits
        finally:
            self.maybe_compiling = False

    def finish_prefill(self, slot: int, logits, temperature: float,
                       top_p: Optional[float], seed: int, *,
                       rng_skip: int = 0) -> int:
        """Close a prefill: sample the request's FIRST token from the
        final chunk's ``logits``, install the slot's tick-side
        sampling state, and mark the lane live. The int() readback is
        the one per-request host sync (TTFT wants the token now).
        ``rng_skip`` (default 0) resumes the request's sample stream
        ``rng_skip`` tokens in — the forced-prefix continuation used
        by token-exact request migration (`_first_token`)."""
        self.maybe_compiling = (
            ("first_token",) not in self._seen_shapes)
        try:
            with self._ctx():
                temp = jnp.float32(temperature)
                tp = jnp.float32(1.0 if top_p is None else top_p)
                tok, rng = _first_token(logits, temp, tp,
                                        jax.random.PRNGKey(seed),
                                        jnp.int32(rng_skip))
                self._note_shape(("first_token",))
                self._toks = self._toks.at[slot].set(tok)
                self._temps = self._temps.at[slot].set(temp)
                self._top_ps = self._top_ps.at[slot].set(tp)
                self._rngs = self._rngs.at[slot].set(rng)
                self._live = self._live.at[slot].set(True)
                # Mirror generate's done0: a first token that IS eos
                # arms the on-device stop immediately, so even the
                # first tick can only re-emit eos for this lane.
                self._done = self._done.at[slot].set(tok == self._eos)
                # hvd: disable=HVD001(the ONE designed per-request sync — TTFT wants the first token now; docs/serving.md)
                return int(tok)
        finally:
            self.maybe_compiling = False

    def prefill(self, slot: int, prompt, temperature: float,
                top_p: Optional[float], seed: int, *,
                max_chunk: Optional[int] = None) -> int:
        """Stream ``prompt`` (1-D int tokens) into ``slot`` in one
        call and return the request's FIRST generated token — the
        begin/chunks/finish composition for callers that do not
        interleave (tests, warmup, simple drivers). Chunks follow the
        binary decomposition (`prefill_chunks`, optionally capped at
        ``max_chunk``), so the set of compiled prefill programs is
        bounded by log2(max_len) — never one per prompt length."""
        prompt = np.asarray(prompt)
        self.begin_prefill(slot)
        logits = None
        off = 0
        for c in prefill_chunks(int(prompt.shape[0]), max_chunk):
            logits = self.prefill_chunk(slot, prompt[off:off + c])
            off += c
        return self.finish_prefill(slot, logits, temperature, top_p,
                                   seed)

    # -- the tick (split for pipelining) ------------------------------

    @hot_path
    def tick_dispatch(self) -> TickHandle:
        """Enqueue one vmapped decode tick over every slot and start
        the async device->host copy of its token buffer; returns
        immediately (jax async dispatch). Pair with `tick_sync` —
        ideally AFTER dispatching the next tick, so the transfer and
        the host bookkeeping hide behind device compute."""
        self.maybe_compiling = ("tick",) not in self._seen_shapes
        try:
            with self._ctx():
                (self._cache, self._toks, self._rngs,
                 self._done) = slot_decode_tick(
                    self.dec_model, self.params, self._cache,
                    self._toks, self._temps, self._top_ps, self._rngs,
                    self._live, self._done, self._eos)
            self._note_shape(("tick",))
        finally:
            self.maybe_compiling = False
        toks = self._toks
        try:
            toks.copy_to_host_async()
        except AttributeError:   # older jax.Array without the method
            pass
        return TickHandle(toks)

    @staticmethod
    @hot_path
    def tick_sync(handle: TickHandle) -> np.ndarray:
        """Block for one dispatched tick's [num_slots] token vector."""
        # The pipelined ring's DESIGNED sync point: the scheduler calls
        # this only after dispatching the next tick, so the read hides
        # behind device compute (metrics: ticks_overlapped).
        return np.asarray(handle.toks)  # hvd: disable=HVD001(the one designed sync of the tick ring)

    def tick(self) -> np.ndarray:
        """Synchronous tick (dispatch + immediate sync) — the
        non-pipelined flavor tests and simple drivers use; the
        scheduler's hot path uses the split pair."""
        return self.tick_sync(self.tick_dispatch())

    # -- speculative rounds (docs/serving.md "Decode fast path") ------

    @hot_path
    def spec_round(self):
        """One batched draft-verify round over every lane: the draft
        proposes ``spec_k`` tokens per live lane, the target verifies
        each lane's block in one chunked append, and 1..k+1 tokens
        retire per lane — bitwise the target's greedy stream. Returns
        ``(emitted [L, k+1], n_emit [L], proposed [L])`` numpy; the
        read is the round's ONE host sync (acceptance is
        data-dependent — the scheduler must see the tokens to retire
        and truncate), amortized over every retired token."""
        assert self.spec_on, "spec_round on a pool without spec_draft"
        k = self.spec_k if self.spec_cap is None \
            else max(1, min(self.spec_k, int(self.spec_cap)))
        self.maybe_compiling = ("spec_round", k) not in self._seen_shapes
        try:
            with self._ctx():
                (self._cache, self._drf_cache, emitted, n_emit,
                 self._done, self._toks, proposed) = slot_spec_round(
                    self.dec_model, self.drf_model, self.params,
                    self.drf_params, self._cache, self._drf_cache,
                    self._toks, self._live, self._done, self._eos,
                    k)
            self._note_shape(("spec_round", k))
        finally:
            self.maybe_compiling = False
        emitted = np.asarray(emitted)  # hvd: disable=HVD001(the spec round's ONE designed sync — acceptance counts are data-dependent and every retired token rides this read; docs/serving.md)
        n_emit = np.asarray(n_emit)  # hvd: disable=HVD001(rides the same designed spec-round sync — the device work is already complete)
        proposed = np.asarray(proposed)  # hvd: disable=HVD001(rides the same designed spec-round sync)
        return emitted, n_emit, proposed

    # -- warmup -------------------------------------------------------

    def warmup(self, max_chunk: Optional[int] = None) -> dict:
        """Precompile the serving hot path before the first request:
        slot reset, every power-of-two prefill chunk a prompt can
        decompose into (capped at ``max_chunk`` when the scheduler
        caps chunks), the first-token sample, and the vmapped decode
        tick. All programs land in the compile-keyed cache this pool
        already consults (`_seen_shapes`), so the first request of any
        prompt shape is a jit-cache hit — no XLA compile in the hot
        path, nothing for the watchdog's `maybe_compiling` exemption
        to special-case. Runs on the caller's thread; lane 0 is used
        as scratch and re-zeroed after."""
        t0 = time.time()
        before = self.compiles
        cap = self.model.max_len
        if max_chunk is not None and max_chunk >= 1:
            cap = min(cap, int(max_chunk))
        cap = 1 << (max(1, cap).bit_length() - 1)   # pow2 floor
        sizes = [1 << b for b in range(cap.bit_length())]
        logits = None
        for c in sizes:
            self.begin_prefill(0)
            logits = self.prefill_chunk(0, np.zeros((c,), np.int32))
        self.finish_prefill(0, logits, 0.0, None, 0)
        if self.spec_on:
            # Spec mode replaces the S=1 tick with the round (the
            # scheduler never dispatches a plain tick), so warm the
            # round INSTEAD of paying a dead full-model tick compile;
            # its program shape is occupancy-independent (live/done
            # are traced).
            self.spec_round()
        else:
            self.tick_sync(self.tick_dispatch())
        # Lane 0 back to pristine FREE state (reset clears live/done).
        self.begin_prefill(0)
        with self._ctx():
            self._toks = self._toks.at[0].set(0)
            self._temps = self._temps.at[0].set(0.0)
            self._top_ps = self._top_ps.at[0].set(1.0)
        return {"compiles": self.compiles - before,
                "seconds": time.time() - t0,
                "prefill_sizes": sizes}

    def free(self, slot: int):
        """Retire a slot: zero its rows (cost hygiene + trivially
        inspectable state), clear its live/done flags (the tick stops
        advancing it), and return it to the free list."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        with self._ctx():
            self._cache = slot_reset(self.dec_model, self._cache,
                                     jnp.int32(slot))
            if self.spec_on:
                self._drf_cache = slot_reset(
                    self.drf_model, self._drf_cache, jnp.int32(slot))
            self._live = self._live.at[slot].set(False)
            self._done = self._done.at[slot].set(False)
            # Neutral sampling state so the freed lane's masked decode
            # stays cheap and deterministic.
            self._toks = self._toks.at[slot].set(0)
            self._temps = self._temps.at[slot].set(0.0)
            self._top_ps = self._top_ps.at[slot].set(1.0)
        self._free.append(slot)
