"""Slot-pool KV cache: the device state behind continuous batching.

The linear decode cache (`parallel.tensor.ParallelSelfAttention`,
``decode=True``) keeps ONE scalar ``cache_index`` shared by the whole
batch — every row must sit at the same fill level, which is exactly
what continuous batching breaks (each slot holds a different request
at a different depth). `models.transformer`'s slot primitives
generalize that cache to a pool: every leaf gains a leading
[num_slots] axis (the per-layer fill scalars become per-slot vectors),
prefill streams a prompt into ONE slot through the `chunked_prefill`
cache-wide-mask path, and the decode tick vmaps the B=1 decode step
over the slot axis. This module wraps those primitives with the
host-side bookkeeping the scheduler needs: a free list, per-slot
sampling state (temperature / top_p / RNG stream), and reset-on-retire
hygiene.

Slot lifecycle::

    FREE --alloc()--> prefill() [reset + stream] --> ACTIVE --tick()*
      ^                                                           |
      +------------------------- free() --------------------------+

A slot is zeroed TWICE per recycle, for two different reasons. At
`prefill()` for correctness: a freed slot keeps riding the shared
vmapped tick while others decode, so by admission time its fill index
has crept to garbage — prefilling without a reset would append the
prompt at that index (shifted RoPE, garbage prefix attended). At
`free()` for cost: restarting the idle creep from 0 keeps the
prefix-attention trip count — which every OTHER slot pays through the
shared vmapped loop — following the ticks-since-free, not the retired
request's full length.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from horovod_tpu.models.transformer import (
    TransformerLM, init_slot_cache, prefill_chunks, sample_token,
    slot_decode_model, slot_decode_tick, slot_prefill_chunk,
    slot_reset,
)
from horovod_tpu.parallel.mesh import use


@jax.jit
def _first_token(logits, temp, top_p, key):
    """First-token sample closing the prefill: split the request key
    exactly as `generate` does (``rng, r0 = split(key)``; the tick
    keeps splitting ``rng``), so a request's sample stream is
    reproducible from its seed regardless of which slot it lands in or
    what else shares the batch."""
    rng, r0 = jax.random.split(key)
    tok = sample_token(logits, temp, top_p, r0)
    return tok.astype(jnp.int32), rng


# A FREE slot is re-zeroed after idling this many ticks. Idle lanes
# ride the shared vmapped tick and creep their fill index; free()'s
# reset restarts the creep, but a slot that sits in the free list
# forever (LIFO alloc under partial occupancy) would otherwise creep
# unboundedly — and the vmapped prefix-attention loop runs to the MAX
# lane's trip count, so every ACTIVE slot would pay for it. The bound
# caps the waste at ceil(64/decode_prefix_block) ≈ 1 extra prefix
# block per lane at the default block size.
RESET_IDLE_TICKS = 64


class SlotPool:
    """A fixed pool of ``num_slots`` decode slots over one shared
    slot-pool KV cache.

    All device work (prefill chunks, the vmapped tick, slot resets)
    happens on the caller's thread — the engine's dispatch thread —
    so jax never sees concurrent mutation of the pool state.
    """

    def __init__(self, model: TransformerLM, params, num_slots: int,
                 *, mesh=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.model = model
        self.dec_model = slot_decode_model(model)
        self.params = params
        self.num_slots = num_slots
        self.mesh = mesh
        self._cache = init_slot_cache(model, num_slots)
        self._toks = jnp.zeros((num_slots,), jnp.int32)
        self._temps = jnp.zeros((num_slots,), jnp.float32)
        self._top_ps = jnp.ones((num_slots,), jnp.float32)
        self._rngs = jnp.stack(
            [jax.random.PRNGKey(i) for i in range(num_slots)])
        self._free: List[int] = list(range(num_slots))
        # Host-side ticks since each slot's last reset (see
        # RESET_IDLE_TICKS).
        self._idle_ticks = np.zeros((num_slots,), np.int64)
        # Compile awareness for the engine watchdog: True while a
        # device call whose shape this pool has not executed before is
        # in flight — a first-time XLA compile can take arbitrarily
        # long and must not read as a stuck tick (stuck detection is
        # suppressed while set). Shapes already seen are jit-cache
        # hits, so the flag clears in microseconds for warm calls.
        self.maybe_compiling = False
        self._seen_shapes: set = set()

    def _ctx(self):
        return use(self.mesh) if self.mesh is not None \
            else contextlib.nullcontext()

    def clone_fresh(self) -> "SlotPool":
        """A brand-new pool over the same model/params/mesh — the
        engine watchdog's restart primitive (docs/resilience.md). The
        old pool may be mid-tick in a hung dispatch thread, so its
        cache and free-list are untrusted; a clone starts from zeroed
        slots. Compiled tick/prefill programs are keyed by the model
        config and shapes, both unchanged, so the clone recompiles
        nothing."""
        fresh = SlotPool(self.model, self.params, self.num_slots,
                         mesh=self.mesh)
        # The jit cache is process-global: shapes this pool compiled
        # are warm for the clone too.
        fresh._seen_shapes = set(self._seen_shapes)
        return fresh

    def fill_indices(self) -> np.ndarray:
        """Per-slot cache fill index, maxed across layers (and the
        pos_index at learned-position models) — introspection for
        tests and debugging (e.g. asserting the idle-creep bound)."""
        from jax.tree_util import tree_flatten_with_path
        flat, _ = tree_flatten_with_path(self._cache)
        idx = [np.asarray(leaf) for path, leaf in flat
               if "index" in str(path)]
        assert idx, "slot cache has no index leaves"
        return np.max(np.stack(idx), axis=0)

    # -- occupancy ----------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def busy_slots(self) -> int:
        return self.num_slots - len(self._free)

    def has_free(self) -> bool:
        return bool(self._free)

    # -- lifecycle ----------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Claim a free slot; None when the pool is full. The slot's
        device rows are NOT assumed clean — `prefill` re-zeroes them
        at use time, because a freed slot keeps riding the shared
        vmapped tick while other slots decode, creeping its fill
        index past whatever `free` zeroed."""
        if not self._free:
            return None
        return self._free.pop()

    def prefill(self, slot: int, prompt, temperature: float,
                top_p: Optional[float], seed: int) -> int:
        """Stream ``prompt`` (1-D int tokens) into ``slot`` and return
        the request's FIRST generated token.

        Starts with a `slot_reset`: the slot has been ticking while
        free (see `alloc`), so its fill index is nonzero garbage by
        now — prefilling without the reset appends the prompt at that
        index with shifted RoPE offsets and attends the idle-decode
        garbage as prefix (token corruption, found by staggered-
        arrival review). Chunks then follow the binary decomposition
        (`prefill_chunks`), so the set of compiled prefill programs is
        bounded by log2(max_len) — never one per prompt length.
        """
        prompt = np.asarray(prompt)
        chunks = prefill_chunks(int(prompt.shape[0]))
        self.maybe_compiling = (
            ("first_token",) not in self._seen_shapes
            or any(("prefill", c) not in self._seen_shapes
                   for c in chunks))
        try:
            with self._ctx():
                self._cache = slot_reset(self.dec_model, self._cache,
                                         jnp.int32(slot))
                self._idle_ticks[slot] = 0
                off = 0
                for c in chunks:
                    self._cache, logits = slot_prefill_chunk(
                        self.dec_model, self.params, self._cache,
                        jnp.int32(slot),
                        jnp.asarray(prompt[off:off + c], jnp.int32))
                    self._seen_shapes.add(("prefill", c))
                    off += c
                temp = jnp.float32(temperature)
                tp = jnp.float32(1.0 if top_p is None else top_p)
                tok, rng = _first_token(logits, temp, tp,
                                        jax.random.PRNGKey(seed))
                self._seen_shapes.add(("first_token",))
                # Install the slot's tick-side sampling state.
                self._toks = self._toks.at[slot].set(tok)
                self._temps = self._temps.at[slot].set(temp)
                self._top_ps = self._top_ps.at[slot].set(tp)
                self._rngs = self._rngs.at[slot].set(rng)
                return int(tok)
        finally:
            self.maybe_compiling = False

    def tick(self) -> np.ndarray:
        """One continuous-batching decode tick over every slot; returns
        the [num_slots] next-token vector (host). The caller decides
        which entries belong to live requests. Long-idle FREE slots
        are re-zeroed afterwards (`RESET_IDLE_TICKS`): a never-
        allocated lane must not creep its fill index — and with it the
        shared prefix-attention trip count — for the engine's
        lifetime."""
        self.maybe_compiling = ("tick",) not in self._seen_shapes
        with self._ctx():
            try:
                self._cache, self._toks, self._rngs = slot_decode_tick(
                    self.dec_model, self.params, self._cache,
                    self._toks, self._temps, self._top_ps, self._rngs)
                self._seen_shapes.add(("tick",))
            finally:
                self.maybe_compiling = False
            toks = np.asarray(self._toks)
            self._idle_ticks += 1
            for slot in self._free:
                if self._idle_ticks[slot] >= RESET_IDLE_TICKS:
                    self._cache = slot_reset(self.dec_model,
                                             self._cache,
                                             jnp.int32(slot))
                    self._idle_ticks[slot] = 0
            return toks

    def free(self, slot: int):
        """Retire a slot: zero its rows (cost hygiene — see module
        doc; `prefill` re-zeroes for correctness) and return it to the
        free list."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        with self._ctx():
            self._cache = slot_reset(self.dec_model, self._cache,
                                     jnp.int32(slot))
            self._idle_ticks[slot] = 0
            # Neutral sampling state so the freed lane's garbage decode
            # stays cheap and deterministic.
            self._toks = self._toks.at[slot].set(0)
            self._temps = self._temps.at[slot].set(0.0)
            self._top_ps = self._top_ps.at[slot].set(1.0)
        self._free.append(slot)
