"""horovod_tpu.serving — in-process continuous-batching serving engine.

The layer that turns concurrent requests into batched device work:

* `engine.ServingEngine` — thin `submit()`/`shutdown()` API over ONE
  background dispatch thread (the reference's background-coordinator
  architecture, pointed at decode scheduling).
* `scheduler.ContinuousBatchingScheduler` — iteration-level batching:
  finished sequences retire and queued prompts prefill into freed
  slots each tick, keeping the decode batch full under load.
* `slots.SlotPool` — the slot-pool KV cache generalizing the linear
  cache's scalar fill index to per-slot state.
* `paging.BlockPool` / `paging.PagedSlotPool` — the paged KV cache:
  device KV carved into refcounted fixed-size blocks (block tables,
  copy-on-write, LRU-cached shared prompt prefixes) so capacity
  follows ACTUAL lengths instead of num_slots x max_len, and a
  cache-hit system prompt skips its prefill
  (`ServingEngine(paged=True)`).
* `router.ServingRouter` — fleet failover over N engine replicas:
  health-gated + load-aware routing, retry budgets, hedged
  slow-starters, and token-exact migration of in-flight streams off
  dead replicas (docs/serving.md "Fleet failover").
* `disagg.DisaggRouter` / `transfer` — disaggregated prefill/decode
  placement: a dedicated prefill pool runs prompts, the KV blocks
  themselves migrate (digest-verified) into the decode pool's prefix
  cache, and the stream resumes mid-flight bitwise-exact
  (``ServingRouter(disagg=...)`` / ``HVD_DISAGG=1``; docs/serving.md
  "Disaggregated serving").
* `admission` — bounded queue, deadlines, cancellation, load shedding
  (degrade by shedding, never by hanging).
* `metrics` — TTFT/TPOT/tokens-per-second with p50/p95, queue depth,
  slot occupancy, paged-block occupancy + prefix-cache hit rates.

See docs/serving.md for the architecture and tuning guide.
"""

from horovod_tpu.serving.admission import (
    AdmissionQueue, DeadlineExceededError, EngineClosedError,
    QueueFullError, SamplingParams, ServingError,
)
from horovod_tpu.serving.disagg import DisaggRouter
from horovod_tpu.serving.engine import RequestHandle, ServingEngine
from horovod_tpu.serving.metrics import EngineMetrics
from horovod_tpu.serving.paging import BlockPool, PagedSlotPool
from horovod_tpu.serving.router import (
    RetryBudget, RouterHandle, ServingRouter,
)
from horovod_tpu.serving.scheduler import (
    CompletedRequest, ContinuousBatchingScheduler,
)
from horovod_tpu.serving.slots import Admission, SlotPool
from horovod_tpu.serving.transfer import (
    BlockTransfer, TransferCompatError, TransferError,
    TransferExportError, TransferVerifyError, export_blocks,
    ingest_blocks,
)

__all__ = [
    "ServingEngine", "RequestHandle", "CompletedRequest",
    "SamplingParams", "SlotPool", "ContinuousBatchingScheduler",
    "AdmissionQueue", "EngineMetrics", "ServingError",
    "QueueFullError", "DeadlineExceededError", "EngineClosedError",
    "Admission", "BlockPool", "PagedSlotPool",
    "ServingRouter", "RouterHandle", "RetryBudget",
    "DisaggRouter", "BlockTransfer", "TransferError",
    "TransferExportError", "TransferCompatError",
    "TransferVerifyError", "export_blocks", "ingest_blocks",
]
