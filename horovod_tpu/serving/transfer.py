"""KV-block transfer between paged pools (disaggregated serving).

Disaggregated prefill/decode (docs/serving.md "Disaggregated
serving") splits the serving workload MPMD-style: a PREFILL pool runs
the compute-bound prompt pass, a DECODE pool runs the
bandwidth-bound token loop, and the thing that crosses between them
is the KV cache itself — the filled blocks of the finished prefill —
not the tokens. This module is the wire format and the two
primitives:

* **`export_blocks`** — pull a finished prompt's FULL block rows out
  of a source `PagedSlotPool` as host (or device-resident) buffers,
  stamped with two digest layers: PR 7's blake2b *chain* digests
  (each block's identity commits to the entire token prefix behind
  it) and per-block *byte* digests over the exported KV rows
  themselves, bound to the chain (content x position).
* **`ingest_blocks`** — verify both layers against the manifest and
  graft the rows into a DESTINATION pool's `BlockPool` as
  refcount-0 LRU-resident cached blocks (fresh block ids; the
  destination's own allocator owns them from the first instant).
  Pools may sit on different meshes: rows re-commit under the
  destination's `safe_spec` layouts (`put_like` / the pool's
  `shard_paged_pools` re-commit), so sharded -> unsharded, 2 -> 4
  device and every other layout pair ingest identically.

The graft deliberately lands in the PREFIX CACHE, not in a live
lane: the decode engine then admits the request through its ordinary
front door with the prefill's first sampled token as a one-token
forced prefix, `BlockPool.match` hits the grafted chain, and prefill
covers only the sub-block prompt tail — composing two properties the
test suite already pins bitwise (prefix-cache hits and forced-prefix
continuation) instead of inventing a third resume path.

Any failure — geometry mismatch, digest mismatch (the
`disagg.block_corrupt` chaos site flips a byte here), an export that
raced the allocator — raises a typed `TransferError`; callers fall
back to PR 9's token-level forced-prefix recompute, loudly (counter +
event), and the stream stays bitwise-exact either way.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import List, Optional, Tuple

import numpy as np

import jax

from horovod_tpu.models.transformer import gather_block_rows
from horovod_tpu.obs import spans as _spans
from horovod_tpu.parallel.mesh import put_like
from horovod_tpu.resilience import chaos
from horovod_tpu.serving.admission import ServingError

_DIGEST_SIZE = 16
_EXPORT_RETRIES = 5


class TransferError(ServingError):
    """A KV-block transfer could not be completed; callers fall back
    to token-level forced-prefix recompute."""


class TransferExportError(TransferError):
    """Export raced the source allocator past its retry budget."""


class TransferCompatError(TransferError):
    """Destination pool geometry/dtype does not match the manifest."""


class TransferVerifyError(TransferError):
    """Digest verification failed on ingest — the bytes on the wire
    are not the bytes the manifest committed to."""


@dataclasses.dataclass(frozen=True)
class BlockTransfer:
    """One prefill->decode handoff manifest: the prompt it came from,
    the block rows (one stacked [n, 1, block_size, ...] array per
    cache leaf), and the two digest layers binding them together.

    ``rows`` are numpy in ``"host"`` mode (bounced through the host —
    always works, any layout pair) or jax Arrays in ``"device"`` mode
    (gathered on the source mesh; ingest `device_put`s them into the
    destination layout). Everything else is host metadata.
    """

    prompt: np.ndarray                       # int64 [P]
    emitted: Tuple[int, ...]                 # tokens prefill sampled
    block_size: int
    chain_digests: Tuple[bytes, ...]         # PR 7 prefix chain
    byte_digests: Tuple[bytes, ...]          # KV-row content x chain
    rows: List                               # per-leaf [n, 1, bs, ...]
    kv_shapes: Tuple[Tuple[int, ...], ...]   # per-leaf row shape [1:]
    kv_dtypes: Tuple[str, ...]
    mode: str = "host"
    trace_id: str = ""
    # Causal-span parent for the transfer.* spans the ingest side
    # emits: the exporter's handoff span id, so both halves of the
    # handoff hang under ONE node of the request's trace tree.
    parent_span: str = ""
    t_export: float = 0.0

    @property
    def num_blocks(self) -> int:
        return len(self.chain_digests)

    @property
    def nbytes(self) -> int:
        return int(sum(r.nbytes for r in self.rows))


def _byte_digest(leaf_rows: List[np.ndarray], chain: bytes) -> bytes:
    """Content digest of ONE transferred block: the block's row bytes
    from every cache leaf, bound to its chain digest — so a row that
    is valid KV for some OTHER prefix position still fails verify."""
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    for r in leaf_rows:
        h.update(np.ascontiguousarray(r).tobytes())
    h.update(chain)
    return h.digest()


def export_blocks(pool, prompt, emitted=(), *, mode: str = "host",
                  trace_id: str = "",
                  parent_span: str = "") -> Optional[BlockTransfer]:
    """Extract ``prompt``'s full resident prefix blocks from a
    `PagedSlotPool` as a `BlockTransfer`, or None when there is
    nothing worth shipping (non-paged pool, prefix cache off, prompt
    shorter than one block, or no blocks resident).

    Runs on the SOURCE engine's dispatch thread (or after it has
    quiesced): the rows are read behind an epoch check — the
    allocator's `_epoch` is recorded before the digest lookup and
    re-checked after the host read, and a bump in between (an evict
    recycling one of our blocks mid-gather) retries the whole export.
    Epoch-stable implies content-stable: every allocator mutation
    bumps `_epoch`, and committed jax arrays are immutable.
    """
    if mode not in ("host", "device"):
        raise ValueError(
            f"transfer mode must be host|device, got {mode!r}")
    blocks = getattr(pool, "blocks", None)
    if blocks is None or not getattr(blocks, "prefix_cache", False):
        return None
    bs = pool.block_size
    # hvd: disable=HVD001(prompt is host-side tokens from the router, never a device array — no sync)
    prompt = np.ascontiguousarray(np.asarray(prompt, np.int64))
    n = len(prompt) // bs
    if n == 0:
        return None
    sid = _spans.begin_span("transfer.export", trace_id=trace_id,
                            parent_id=parent_span, mode=mode)
    chain = blocks._chain(prompt, n)
    for _ in range(_EXPORT_RETRIES):
        epoch = blocks._epoch
        bids = []
        for h in chain:
            bid = blocks._cache.get(h)
            if bid is None:
                break
            bids.append(bid)
        if not bids:
            _spans.end_span(sid, status="not_resident")
            return None
        with pool._ctx():
            dev_rows = gather_block_rows(pool._pools, bids)
        if mode == "host":
            # hvd: disable=HVD001(the transfer's designed host bounce — export runs off the decode tick ring, once per handoff)
            rows = [np.asarray(r) for r in dev_rows]
        else:
            rows = [r for r in dev_rows]
            jax.block_until_ready(rows)  # hvd: disable=HVD001(materialize before the epoch re-check — once per handoff, off the tick ring)
        if blocks._epoch != epoch:
            continue   # an evict/alloc raced the gather — retry
        m = len(bids)
        if mode == "host":
            host_rows = rows
        else:
            # hvd: disable=HVD001(digest wants host bytes; rows are already ready — once per handoff)
            host_rows = [np.asarray(r) for r in rows]
        byte_digests = tuple(
            _byte_digest([hr[i] for hr in host_rows], chain[i])
            for i in range(m))
        tr = BlockTransfer(
            prompt=prompt, emitted=tuple(int(t) for t in emitted),
            block_size=bs, chain_digests=tuple(chain[:m]),
            byte_digests=byte_digests, rows=rows,
            kv_shapes=tuple(tuple(r.shape[1:]) for r in rows),
            kv_dtypes=tuple(str(np.dtype(r.dtype)) for r in rows),
            mode=mode, trace_id=trace_id, parent_span=parent_span,
            t_export=time.time())
        _spans.end_span(sid, status="ok", blocks=m,
                        bytes=tr.nbytes)
        return tr
    _spans.end_span(sid, status="raced")
    raise TransferExportError(
        f"block export raced the allocator {_EXPORT_RETRIES} times "
        f"(pool under eviction pressure)")


def _check_compat(pool, tr: BlockTransfer):
    if tr.block_size != pool.block_size:
        raise TransferCompatError(
            f"block_size mismatch: transfer {tr.block_size}, "
            f"destination {pool.block_size}")
    if len(tr.rows) != len(pool._pools):
        raise TransferCompatError(
            f"cache leaf count mismatch: transfer {len(tr.rows)}, "
            f"destination {len(pool._pools)}")
    for k, (r, p) in enumerate(zip(tr.rows, pool._pools)):
        if tuple(r.shape[1:]) != tuple(p.shape[1:]):
            raise TransferCompatError(
                f"leaf {k} row shape mismatch: transfer "
                f"{tuple(r.shape[1:])}, destination "
                f"{tuple(p.shape[1:])}")
        if np.dtype(r.dtype) != np.dtype(p.dtype):
            raise TransferCompatError(
                f"leaf {k} dtype mismatch: transfer {r.dtype}, "
                f"destination {p.dtype}")


def ingest_blocks(pool, tr: BlockTransfer) -> int:
    """Verify ``tr`` and graft its blocks into ``pool``'s prefix
    cache under fresh destination block ids. Returns how many blocks
    were NEWLY adopted (already-resident digests are skipped —
    ingest is idempotent, so a re-offered transfer after a failed
    handoff costs nothing).

    Runs on the DESTINATION engine's dispatch thread. Adoption is
    capacity-aware: it stops once taking another block would evict a
    block of its own chain (tiny pools), and a partial graft is fine
    — `match` simply hits a shorter prefix and prefill covers more
    tail. Any verification failure raises `TransferVerifyError` and
    leaves the pool untouched.
    """
    blocks = getattr(pool, "blocks", None)
    if blocks is None or not getattr(blocks, "prefix_cache", False):
        return 0
    m = tr.num_blocks
    vsid = _spans.begin_span("transfer.verify",
                             trace_id=tr.trace_id,
                             parent_id=tr.parent_span, blocks=m)
    try:
        _check_compat(pool, tr)
        if not (len(tr.byte_digests) == m
                and all(len(r) == m for r in tr.rows)):
            raise TransferVerifyError(
                f"manifest arity mismatch: {m} chain digests, "
                f"{len(tr.byte_digests)} byte digests, rows "
                f"{[len(r) for r in tr.rows]}")
        # Host copies for verification (and for the corrupt drill —
        # flipping the copy models a wire fault without touching the
        # caller's buffers).
        # hvd: disable=HVD001(verify wants host bytes; once per handoff, off the tick ring)
        rows_h = [np.array(r, copy=True) for r in tr.rows]
        if chaos.fires("disagg.block_corrupt"):
            rows_h[0].view(np.uint8).reshape(-1)[0] ^= 0xFF
        # Layer 1: the chain digests must be the prompt's own chain —
        # block i's identity commits to tokens[0 : (i+1)*block_size].
        expect = blocks._chain(tr.prompt, m)
        if tuple(expect) != tuple(tr.chain_digests):
            raise TransferVerifyError(
                "chain digest mismatch: manifest digests are not "
                "the prompt's prefix chain")
        # Layer 2: row bytes must be the bytes the exporter hashed.
        for i in range(m):
            got = _byte_digest([r[i] for r in rows_h],
                               tr.chain_digests[i])
            if got != tr.byte_digests[i]:
                raise TransferVerifyError(
                    f"block {i} byte digest mismatch (transfer "
                    f"corrupted in flight)")
    except TransferError as e:
        _spans.end_span(vsid, status="failed",
                        error=type(e).__name__)
        raise
    _spans.end_span(vsid, status="ok")
    isid = _spans.begin_span("transfer.ingest",
                             trace_id=tr.trace_id,
                             parent_id=tr.parent_span, blocks=m)
    # Re-commit the row stacks under the destination's layouts ONCE:
    # the stacked [m, 1, bs, ...] arrays are rank-aligned with the
    # pool leaves ([num_blocks, 1, bs, ...]), so `put_like` lands the
    # heads shards exactly where the destination leaf holds them —
    # whatever mesh (or none) the rows came from.
    rows_dev = [put_like(r, pool._pools[k])
                for k, r in enumerate(tr.rows)]
    # Adopt in chain order; stop before cannibalizing our own chain
    # (evicting an earlier grafted block to make room for a later one
    # would break the contiguous prefix `match` needs).
    ours = set()
    adopted = 0
    for i in range(m):
        h = tr.chain_digests[i]
        if h in blocks._cache:
            ours.add(blocks._cache[h])
            continue
        evictable = sum(1 for bid in blocks._lru
                        if bid not in ours)
        if blocks.free_blocks + evictable < 1:
            break
        bid = blocks.adopt(h)
        if bid is None:
            break
        ours.add(bid)
        with pool._ctx():
            for k, leaf in enumerate(rows_dev):
                pool._pools[k] = pool._pools[k].at[bid].set(leaf[i])
        adopted += 1
    if adopted and pool.mesh is not None:
        # Restore the committed safe_spec layouts after the scatter
        # (a `.at[].set` can decay the sharding on some backends).
        from horovod_tpu.models.transformer import shard_paged_pools
        with pool._ctx():
            pool._pools = shard_paged_pools(pool._pools, pool.mesh)
    _spans.end_span(isid, adopted=adopted)
    return adopted
